"""HG3xx — static contracts for ``pl.pallas_call`` sites.

Checked per call site, from literals and best-effort constant folding only
(unresolvable values are skipped, never guessed):

HG301  block shapes: last dim % 128, second-to-last dim % 8 (== 1 allowed
       — Mosaic accepts singleton sublane blocks when the dim is full).
HG302  index_map contracts: lambda arity == grid rank (+ scalar-prefetch
       operands), returned tuple rank == block rank, and — when grid,
       block, and array dims all fold to ints — the mapped block stays in
       bounds.
HG303  dtype-dependent sublane tiling: 16-bit dtypes need sublane % 16,
       8-bit need % 32 (checked on out_specs, where out_shape names the
       dtype).
HG304  kernel writes to an output ref with an explicit dtype that differs
       from the declared out_shape dtype.
"""

from __future__ import annotations

import ast
from typing import Optional

from tools.hglint.callgraph import PALLAS_FQNS, CallGraph, CallSite, \
    _unwrap_partial
from tools.hglint.loader import (
    DTYPE_SUBLANE,
    ConstEnv,
    ModuleInfo,
    dtype_name,
    resolve_fqn,
)
from tools.hglint.model import Finding

LANE = 128
SUBLANE = 8


def check(cg: CallGraph, modules: list) -> list:
    findings = []
    for site in cg.calls:
        fqn = resolve_fqn(site.node.func, site.mod)
        if fqn not in PALLAS_FQNS:
            continue
        findings += _check_call(cg, site)
    return findings


# ----------------------------------------------------------------- per call


def _check_call(cg: CallGraph, site: CallSite) -> list:
    call, mod = site.node, site.mod
    fi = cg.functions.get(site.fn_key) if site.fn_key else None
    env = ConstEnv.for_function(mod, fi.node) if fi else ConstEnv(mod)
    scope = fi.qualpath if fi else "<module>"

    kw = {k.arg: k.value for k in call.keywords if k.arg}
    n_scalar = 0
    grid_node = kw.get("grid")
    in_specs = kw.get("in_specs")
    out_specs = kw.get("out_specs")
    gs = kw.get("grid_spec")
    if isinstance(gs, ast.Call):
        gkw = {k.arg: k.value for k in gs.keywords if k.arg}
        grid_node = gkw.get("grid", grid_node)
        in_specs = gkw.get("in_specs", in_specs)
        out_specs = gkw.get("out_specs", out_specs)
        v = env.eval_node(gkw.get("num_scalar_prefetch"))
        if isinstance(v, int):
            n_scalar = v

    grid = env.eval_node(grid_node)
    if isinstance(grid, int):
        grid = (grid,)
    grid_rank = len(grid) if isinstance(grid, tuple) else None

    out_shape_dims, out_dtype = _parse_out_shape(kw.get("out_shape"), env, mod)

    findings = []
    specs = []
    for spec, is_out in _iter_specs(in_specs, out_specs):
        specs.append((spec, is_out))
        findings += _check_spec(
            spec, is_out, env, mod, scope, grid, grid_rank, n_scalar,
            out_shape_dims, out_dtype,
        )
    findings += _check_kernel_dtype(
        cg, site, env, scope, n_scalar, in_specs, out_specs, out_dtype
    )
    return findings


def _iter_specs(in_specs, out_specs):
    if isinstance(in_specs, (ast.List, ast.Tuple)):
        for e in in_specs.elts:
            yield e, False
    elif isinstance(in_specs, ast.Call):
        yield in_specs, False
    if isinstance(out_specs, (ast.List, ast.Tuple)):
        for e in out_specs.elts:
            yield e, True
    elif isinstance(out_specs, ast.Call):
        yield out_specs, True


def _parse_out_shape(node, env: ConstEnv, mod: ModuleInfo):
    """``jax.ShapeDtypeStruct(shape, dtype)`` -> (dims tuple | None, dtype
    name | None)."""
    if not isinstance(node, ast.Call):
        return None, None
    fqn = resolve_fqn(node.func, mod) or ""
    if not fqn.endswith("ShapeDtypeStruct"):
        return None, None
    dims = env.eval_node(node.args[0]) if node.args else None
    if not isinstance(dims, tuple):
        dims = None
    dt = None
    if len(node.args) > 1:
        dt = dtype_name(node.args[1], mod)
    for k in node.keywords:
        if k.arg == "dtype":
            dt = dtype_name(k.value, mod)
        elif k.arg == "shape":
            d = env.eval_node(k.value)
            dims = d if isinstance(d, tuple) else dims
    return dims, dt


# ------------------------------------------------------------- spec checks


def _check_spec(spec, is_out, env, mod, scope, grid, grid_rank, n_scalar,
                out_shape_dims, out_dtype) -> list:
    if not isinstance(spec, ast.Call):
        return []
    fqn = resolve_fqn(spec.func, mod) or ""
    if not fqn.endswith("BlockSpec"):
        return []
    block_node = spec.args[0] if spec.args else None
    index_map = spec.args[1] if len(spec.args) > 1 else None
    for k in spec.keywords:
        if k.arg == "block_shape":
            block_node = k.value
        elif k.arg == "index_map":
            index_map = k.value
    if block_node is None or isinstance(block_node, ast.keyword):
        return []
    block = env.eval_node(block_node)
    if not isinstance(block, tuple):
        return []
    findings = []
    which = "out_specs" if is_out else "in_specs"

    # -- HG301 / HG303: tile alignment --------------------------------------
    if len(block) >= 2:
        last, sub = block[-1], block[-2]
        if isinstance(last, int) and last % LANE:
            findings.append(_f("HG301", mod, block_node, scope,
                               f"{which} block lane dim {last} is not a "
                               f"multiple of {LANE}"))
        if isinstance(sub, int) and sub != 1 and sub % SUBLANE:
            findings.append(_f("HG301", mod, block_node, scope,
                               f"{which} block sublane dim {sub} is not a "
                               f"multiple of {SUBLANE}"))
        req = DTYPE_SUBLANE.get(out_dtype or "", SUBLANE) if is_out \
            else SUBLANE
        if req > SUBLANE and isinstance(sub, int) and sub != 1 \
                and sub % SUBLANE == 0 and sub % req:
            findings.append(_f("HG303", mod, block_node, scope,
                               f"{which} block sublane dim {sub} must be a "
                               f"multiple of {req} for dtype {out_dtype}"))

    # -- HG302: index_map contracts -----------------------------------------
    if isinstance(index_map, ast.Lambda):
        params = [a.arg for a in index_map.args.args]
        if grid_rank is not None and len(params) != grid_rank + n_scalar:
            findings.append(_f(
                "HG302", mod, index_map, scope,
                f"{which} index_map takes {len(params)} args but the grid "
                f"has rank {grid_rank}"
                + (f" (+{n_scalar} scalar-prefetch)" if n_scalar else ""),
            ))
        ret = index_map.body
        ret_elts = list(ret.elts) if isinstance(ret, ast.Tuple) else [ret]
        if len(ret_elts) != len(block):
            findings.append(_f(
                "HG302", mod, index_map, scope,
                f"{which} index_map returns {len(ret_elts)} indices for a "
                f"rank-{len(block)} block",
            ))
        elif is_out and out_shape_dims is not None \
                and isinstance(grid, tuple):
            findings += _bounds_check(
                ret_elts, params, grid, grid_rank, block, out_shape_dims,
                env, mod, index_map, scope, which,
            )
    return findings


def _bounds_check(ret_elts, params, grid, grid_rank, block, dims, env, mod,
                  where, scope, which) -> list:
    """Affine bound check: for return element a*g + b over grid var g with
    everything integer-resolvable, require (max_index + 1) * block_dim <=
    array_dim."""
    findings = []
    for d, (elt, bdim) in enumerate(zip(ret_elts, block)):
        if d >= len(dims):
            break
        adim = dims[d]
        if not isinstance(adim, int) or not isinstance(bdim, int):
            continue
        max_idx = _affine_max(elt, params, grid, grid_rank, env)
        if max_idx is None:
            continue
        if (max_idx + 1) * bdim > adim:
            findings.append(_f(
                "HG302", mod, where, scope,
                f"{which} index_map dim {d} reaches block index {max_idx} "
                f"-> elements up to {(max_idx + 1) * bdim} > array dim "
                f"{adim} (out of bounds for the declared grid)",
            ))
    return findings


def _affine_max(elt, params, grid, grid_rank, env) -> Optional[int]:
    """Max value of an index expression over the grid, for constants,
    bare grid vars, and +/-/* combinations thereof. None when unknown."""
    if isinstance(elt, ast.Constant):
        return elt.value if isinstance(elt.value, int) else None
    if isinstance(elt, ast.Name):
        if elt.id in params:
            pos = params.index(elt.id)
            if grid_rank is not None and pos < grid_rank and \
                    isinstance(grid[pos], int):
                return grid[pos] - 1
            return None
        v = env.eval_node(elt)
        return v if isinstance(v, int) else None
    if isinstance(elt, ast.BinOp) and isinstance(
            elt.op, (ast.Add, ast.Sub, ast.Mult)):
        lhs = _affine_max(elt.left, params, grid, grid_rank, env)
        rhs = _affine_max(elt.right, params, grid, grid_rank, env)
        if lhs is None or rhs is None:
            return None
        # monotone in both operands for non-negative index arithmetic
        if isinstance(elt.op, ast.Add):
            return lhs + rhs
        if isinstance(elt.op, ast.Sub):
            return lhs - 0 if rhs == 0 else None  # conservative
        return lhs * rhs
    return None


# ------------------------------------------------------------------ HG304


def _check_kernel_dtype(cg, site, env, scope, n_scalar, in_specs, out_specs,
                        out_dtype) -> list:
    if out_dtype is None:
        return []
    n_in = _spec_count(in_specs)
    n_out = _spec_count(out_specs)
    if n_in is None or n_out != 1:
        return []
    kernel_expr = _unwrap_partial(site.node.args[0], site.mod) \
        if site.node.args else None
    if kernel_expr is None:
        return []
    key = cg.resolve_callable(kernel_expr, site)
    if key is None:
        return []
    kfi = cg.functions[key]
    out_pos = n_scalar + n_in
    if out_pos >= len(kfi.params):
        return []
    out_param = kfi.params[out_pos]
    findings = []
    for node in ast.walk(kfi.node):
        if not isinstance(node, ast.Assign):
            continue
        for tgt in node.targets:
            if isinstance(tgt, ast.Subscript) and \
                    isinstance(tgt.value, ast.Name) and \
                    tgt.value.id == out_param:
                written = _written_dtype(node.value, kfi.mod)
                if written is not None and written != out_dtype:
                    findings.append(_f(
                        "HG304", kfi.mod, node, kfi.qualpath,
                        f"kernel writes dtype {written} to `{out_param}` "
                        f"but out_shape declares {out_dtype}",
                    ))
    return findings


def _spec_count(specs) -> Optional[int]:
    if isinstance(specs, (ast.List, ast.Tuple)):
        return len(specs.elts)
    if isinstance(specs, ast.Call):
        return 1
    return None


def _written_dtype(value: ast.AST, mod: ModuleInfo) -> Optional[str]:
    """Explicit dtype evidence in the written expression: a top-level
    ``.astype(d)`` or a constructor with ``dtype=d``. Deliberately shallow
    — only the outermost expression counts, so mixed-arithmetic interiors
    don't mislead."""
    if isinstance(value, ast.Call):
        if isinstance(value.func, ast.Attribute) and \
                value.func.attr in ("astype", "view") and value.args:
            return dtype_name(value.args[0], mod)
        for k in value.keywords:
            if k.arg == "dtype":
                return dtype_name(k.value, mod)
    return None


def _f(rule, mod, node, scope, msg) -> Finding:
    return Finding(rule=rule, path=mod.path,
                   line=getattr(node, "lineno", 1), message=msg, scope=scope)
