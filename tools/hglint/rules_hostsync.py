"""HG1xx — host-sync calls reachable from traced (jit/pjit/shard_map/
pallas_call) code.

Every rule here fires only inside functions the taint pass marked as
traced; host-side wrappers may sync freely (that is where syncs belong).
"""

from __future__ import annotations

import ast

from tools.hglint.callgraph import CallGraph
from tools.hglint.loader import own_nodes, resolve_fqn
from tools.hglint.model import Finding

SHAPE_ATTRS = {"shape", "ndim", "size", "dtype"}

#: fqns that are host syncs no matter how they are spelled
_DEVICE_GET = {"jax.device_get"}
_BLOCK_READY = {"jax.block_until_ready"}

#: numpy prefixes — a call into numpy inside traced code materializes host
#: data (np.asarray, np.array, np.nonzero, ...)
_NUMPY_HEADS = ("numpy.",)

#: jnp constructors that silently upload a host value per trace (HG107)
_JNP_UPLOADERS = ("jax.numpy.asarray", "jax.numpy.array")


def check(cg: CallGraph) -> list:
    findings = []
    for fi in cg.traced_functions():
        root = cg.traced[fi.key]
        via = "" if root == fi.key else f" (traced via {_short(root)})"
        np_locals = _numpy_locals(fi)
        for node in own_nodes(fi.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            # -- .item() -----------------------------------------------------
            if isinstance(func, ast.Attribute) and func.attr == "item" \
                    and not node.args and not node.keywords:
                findings.append(_f("HG101", fi, node,
                                   f"`.item()` in traced code{via}"))
                continue
            # -- .block_until_ready() / jax.block_until_ready(x) ------------
            if isinstance(func, ast.Attribute) \
                    and func.attr == "block_until_ready":
                fqn = resolve_fqn(func, fi.mod)
                msg = (f"`{fqn or 'block_until_ready'}` in traced "
                       f"code{via}")
                findings.append(_f("HG105", fi, node, msg))
                continue
            fqn = resolve_fqn(func, fi.mod)
            if fqn is None:
                continue
            if fqn in _BLOCK_READY:
                findings.append(_f("HG105", fi, node,
                                   f"`jax.block_until_ready` in traced "
                                   f"code{via}"))
            elif fqn in _DEVICE_GET:
                findings.append(_f("HG104", fi, node,
                                   f"`jax.device_get` in traced code{via}"))
            elif fqn.startswith(_NUMPY_HEADS):
                findings.append(_f("HG103", fi, node,
                                   f"`{_np_spelling(func)}` call in traced "
                                   f"code{via} — use jnp or hoist to host"))
            elif fqn in ("float", "int", "bool") and len(node.args) == 1 \
                    and not node.keywords:
                if not _shape_derived(node.args[0], fi):
                    findings.append(_f(
                        "HG102", fi, node,
                        f"`{fqn}()` on a possibly-traced value{via} — "
                        f"concretizes under trace",
                    ))
            elif fqn in _JNP_UPLOADERS and node.args:
                src = _host_numpy_source(node.args[0], fi, np_locals)
                if src:
                    findings.append(_f(
                        "HG107", fi, node,
                        f"`{_np_spelling(node.func)}` on host numpy value "
                        f"`{src}` in traced code{via} — a silent "
                        f"host->device transfer baked in per trace; build "
                        f"it with jnp ops or pass it as an argument",
                    ))
    return findings


def _numpy_locals(fi) -> tuple:
    """(names assigned from a ``numpy.*`` call inside this function,
    every locally-bound name — params + any Store) so a parameter or
    local that SHADOWS a numpy module global isn't misread as one."""
    np_names: set = set()
    bound: set = set(fi.params)
    for node in own_nodes(fi.node):
        if isinstance(node, ast.Assign) and len(node.targets) == 1 and \
                isinstance(node.targets[0], ast.Name) and \
                isinstance(node.value, ast.Call):
            fqn = resolve_fqn(node.value.func, fi.mod)
            if fqn and fqn.startswith(_NUMPY_HEADS):
                np_names.add(node.targets[0].id)
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            bound.add(node.id)
    return np_names, bound


def _host_numpy_source(expr: ast.AST, fi, np_locals: tuple):
    """The name of the host numpy value being uploaded, or None: a local
    assigned from ``np.*`` in this function, or a module-level global
    bound to a ``np.*`` call result (unless a parameter/local shadows
    it). Anything else (a traced array, a literal) is a legitimate
    ``jnp.asarray`` and stays silent."""
    np_names, bound = np_locals
    if isinstance(expr, ast.Name):
        if expr.id in np_names:
            return expr.id
        if expr.id in fi.mod.np_globals and expr.id not in bound:
            return expr.id
    return None


def _np_spelling(func: ast.AST) -> str:
    try:
        return ast.unparse(func)
    except Exception:  # pragma: no cover
        return "numpy call"


def _shape_derived(expr: ast.AST, fi) -> bool:
    """True when the cast argument is statically concrete under tracing:
    literals, len(...), shape/ndim/size attributes, static params, or
    arithmetic thereof."""
    if isinstance(expr, ast.Constant):
        return True
    if isinstance(expr, ast.Attribute) and expr.attr in SHAPE_ATTRS:
        return True
    if isinstance(expr, ast.Subscript):
        # x.shape[0]
        return _shape_derived(expr.value, fi)
    if isinstance(expr, ast.Call):
        fn = expr.func
        if isinstance(fn, ast.Name) and fn.id == "len":
            return True
        return False
    if isinstance(expr, ast.Name):
        return expr.id in fi.static_params
    if isinstance(expr, ast.BinOp):
        return _shape_derived(expr.left, fi) and \
            _shape_derived(expr.right, fi)
    if isinstance(expr, ast.UnaryOp):
        return _shape_derived(expr.operand, fi)
    return False


def _short(key: str) -> str:
    return key.rsplit(".", 1)[-1] if "." in key else key


def _f(rule: str, fi, node: ast.AST, msg: str) -> Finding:
    return Finding(rule=rule, path=fi.mod.path,
                   line=getattr(node, "lineno", fi.lineno),
                   message=msg, scope=fi.qualpath)
