#!/usr/bin/env bash
# hgobs telemetry gate: the observability suite — tracing/sampling units,
# the serving span-chain + overhead differential, cross-process peer
# tracing (replication push / catch-up / snapshot transfer span trees),
# the flight recorder, and the HTTP endpoint tests — followed by a live
# end-to-end smoke: start a real ServeRuntime + TelemetryServer and
# scrape /metrics and /healthz over actual HTTP (curl when present,
# stdlib urllib otherwise — CI images without curl still smoke).
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth), and
# chaos.sh (fault injection): this one gates the telemetry plane.
#
# Usage: tools/obs.sh [extra pytest args]
#   tools/obs.sh -k sampling           # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_obs.py \
    tests/test_obs_serving.py \
    tests/test_peer_tracing.py \
    tests/test_flight.py \
    tests/test_obs_http.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/obs.sh: observability tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live smoke: a real runtime behind the real endpoint ---------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import shutil
import subprocess
import sys
import urllib.request

import hypergraphdb_tpu as hg
from hypergraphdb_tpu import obs
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = hg.HyperGraph()
a, b = g.add("smoke-a"), g.add("smoke-b")
g.add_link([a, b], value="smoke-e")
obs.enable()
rt = ServeRuntime(g, ServeConfig(max_linger_s=0.001, top_r=8))
rt.submit_bfs(int(a), max_hops=1).result(timeout=120)
srv = obs.TelemetryServer(
    registries=[rt.stats.registry, g.metrics.registry],
    health=obs.runtime_health(rt),
).start()
try:
    curl = shutil.which("curl")

    def scrape(route: str) -> str:
        url = srv.url + route
        if curl:
            out = subprocess.run(
                [curl, "-fsS", "--max-time", "10", url],
                check=True, capture_output=True, text=True,
            )
            return out.stdout
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read().decode()

    metrics = scrape("/metrics")
    assert "serve_submitted_total" in metrics, metrics[:200]
    assert "graph_mutations_total" in metrics, metrics[:200]
    health = scrape("/healthz")
    assert '"queue_depth"' in health and '"breakers"' in health, health
    print(f"tools/obs.sh smoke: scraped {srv.url} "
          f"({'curl' if curl else 'urllib'}) — metrics + healthz OK")
finally:
    srv.stop()
    rt.close()
    g.close()
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/obs.sh: live endpoint smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/obs.sh: observability gate green"
exit 0
