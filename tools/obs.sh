#!/usr/bin/env bash
# hgobs telemetry gate: the observability suite — tracing/sampling units,
# the serving span-chain + overhead differential, cross-process peer
# tracing (replication push / catch-up / snapshot transfer span trees),
# the flight recorder, the HTTP endpoint tests, and the FLEET plane
# (collector merges, cross-process trace assembly, SLO burn alerts,
# EXPLAIN) — followed by two live smokes over actual HTTP (curl when
# present, stdlib urllib otherwise — CI images without curl still
# smoke): (1) a real ServeRuntime + TelemetryServer scraped at /metrics
# and /healthz; (2) a primary + 2 replicas + front door, the fleet
# collector scraping every node's telemetry port, and /fleet/metrics,
# /fleet/slo, and one joined /fleet/traces/<tid> spanning two processes
# fetched from the door.
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth), and
# chaos.sh (fault injection): this one gates the telemetry plane.
#
# Usage: tools/obs.sh [extra pytest args]
#   tools/obs.sh -k sampling           # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_obs.py \
    tests/test_obs_serving.py \
    tests/test_peer_tracing.py \
    tests/test_flight.py \
    tests/test_obs_http.py \
    tests/test_fleet.py \
    tests/test_slo.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/obs.sh: observability tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live smoke: a real runtime behind the real endpoint ---------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import shutil
import subprocess
import sys
import urllib.request

import hypergraphdb_tpu as hg
from hypergraphdb_tpu import obs
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = hg.HyperGraph()
a, b = g.add("smoke-a"), g.add("smoke-b")
g.add_link([a, b], value="smoke-e")
obs.enable()
rt = ServeRuntime(g, ServeConfig(max_linger_s=0.001, top_r=8))
rt.submit_bfs(int(a), max_hops=1).result(timeout=120)
srv = obs.TelemetryServer(
    registries=[rt.stats.registry, g.metrics.registry],
    health=obs.runtime_health(rt),
).start()
try:
    curl = shutil.which("curl")

    def scrape(route: str) -> str:
        url = srv.url + route
        if curl:
            out = subprocess.run(
                [curl, "-fsS", "--max-time", "10", url],
                check=True, capture_output=True, text=True,
            )
            return out.stdout
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.read().decode()

    metrics = scrape("/metrics")
    assert "serve_submitted_total" in metrics, metrics[:200]
    assert "graph_mutations_total" in metrics, metrics[:200]
    health = scrape("/healthz")
    assert '"queue_depth"' in health and '"breakers"' in health, health
    print(f"tools/obs.sh smoke: scraped {srv.url} "
          f"({'curl' if curl else 'urllib'}) — metrics + healthz OK")
finally:
    srv.stop()
    rt.close()
    g.close()
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/obs.sh: live endpoint smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi

# -- live smoke 2: the FLEET behind the front door ---------------------------
# primary + 2 serving replicas + front door over real HTTP sockets, each
# node's TelemetryServer scraped by the fleet collector via
# HTTPNodeSource; /fleet/metrics, /fleet/slo, and one joined
# /fleet/traces/<tid> spanning two processes fetched from the DOOR.
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import shutil
import subprocess
import time
import urllib.request

import hypergraphdb_tpu as hg
from hypergraphdb_tpu import obs
from hypergraphdb_tpu.obs.fleet import FleetCollector, HTTPNodeSource
from hypergraphdb_tpu.obs.http import TelemetryServer, runtime_health
from hypergraphdb_tpu.obs.slo import fleet_objectives
from hypergraphdb_tpu.obs.trace import Tracer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.replica import (
    FrontDoor,
    LocalBackend,
    ReplicaConfig,
    ReplicaNode,
    RouterConfig,
    frontdoor_server,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

curl = shutil.which("curl")


def scrape(url):
    if curl:
        out = subprocess.run([curl, "-fsS", "--max-time", "10", url],
                             check=True, capture_output=True, text=True)
        return out.stdout
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


net = LoopbackNetwork()
gp = hg.HyperGraph()
pp = HyperGraphPeer.loopback(gp, net, identity="primary")
pp.replication.debounce_s = 0.005
pp.tracer = Tracer(max_finished=256).enable()
pp.start()
hs = [int(gp.add(f"s{i}")) for i in range(8)]
for i in range(7):
    gp.add_link([hs[i], hs[i + 1]], value=f"e{i}")

nodes, tsrvs = [], []
for ident in ("r1", "r2"):
    gr = hg.HyperGraph()
    pr = HyperGraphPeer.loopback(gr, net, identity=ident)
    pr.replication.debounce_s = 0.005
    pr.tracer = Tracer(max_finished=256).enable()
    node = ReplicaNode(gr, pr, ReplicaConfig(
        primary="primary",
        serve=ServeConfig(max_linger_s=0.001, top_r=8, prewarm_aot=False,
                          tracer=pr.tracer),
    ))
    node.start()
    assert node.wait_converged(timeout=60), f"{ident} never converged"
    nodes.append(node)
    tsrvs.append(TelemetryServer(
        registries=[node.runtime.stats.registry, gr.metrics.registry],
        tracer=pr.tracer, health=node.health_probe(),
    ).start())
gp.add("traced-tail")  # a push every replica records under one trace id

prt = ServeRuntime(gp, ServeConfig(max_linger_s=0.001, top_r=8,
                                   prewarm_aot=False))
tsrvs.append(TelemetryServer(
    registries=[prt.stats.registry, gp.metrics.registry],
    tracer=pp.tracer, health=runtime_health(prt),
).start())
fd = FrontDoor(
    LocalBackend("primary", prt, runtime_health(prt), role="primary"),
    [LocalBackend("r1", nodes[0].runtime, nodes[0].health_probe()),
     LocalBackend("r2", nodes[1].runtime, nodes[1].health_probe())],
    RouterConfig(poll_interval_s=0.1),
).start()
col = FleetCollector(
    [HTTPNodeSource("r1", tsrvs[0].url, role="replica"),
     HTTPNodeSource("r2", tsrvs[1].url, role="replica"),
     HTTPNodeSource("primary", tsrvs[2].url, role="primary"),
     fd.fleet_source()],
    poll_interval_s=0.1,
)
col.slo = fleet_objectives(col, windows=((5.0, 14.4), (30.0, 6.0)))
col.start()
fsrv = frontdoor_server(fd, fleet=col).start()
try:
    res = fd.submit({"kind": "bfs", "seed": hs[0], "max_hops": 2,
                     "deadline_s": 10.0})
    assert res["routed_to"], res
    metrics = scrape(fsrv.url + "/fleet/metrics")
    assert 'serve_submitted_total{node="r1"}' in metrics, metrics[:300]
    assert 'node="primary"' in metrics
    slo = json.loads(scrape(fsrv.url + "/fleet/slo"))
    assert "serve_deadline" in slo and "availability" in slo, slo
    joined = None
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline and joined is None:
        col.poll()
        for s in col.fleet_traces():
            if s["n_processes"] >= 2:
                joined = s
                break
        time.sleep(0.05)
    assert joined is not None, "no cross-process trace assembled"
    trace = json.loads(scrape(fsrv.url + f"/fleet/traces/{joined['trace_id']}"))
    assert trace["n_processes"] >= 2, trace["processes"]
    print(f"tools/obs.sh fleet smoke: {fsrv.url} — /fleet/metrics + "
          f"/fleet/slo OK; trace {trace['trace_id']} spans "
          f"{trace['processes']} ({'curl' if curl else 'urllib'})")
finally:
    fsrv.stop()
    col.stop()
    fd.stop()
    prt.close()
    for t in tsrvs:
        t.stop()
    for node in nodes:
        node.stop()
    pp.stop()
    gp.close()
    for node in nodes:
        node.graph.close()
PY
fleet_rc=$?
if [ "$fleet_rc" -ne 0 ]; then
    echo "tools/obs.sh: fleet smoke failed (exit $fleet_rc)" >&2
    exit "$fleet_rc"
fi
echo "tools/obs.sh: observability gate green"
exit 0
