#!/usr/bin/env bash
# hgsub gate: the standing-query tier — subscription manager unit
# contracts (envelopes, deltas, backpressure, long-poll, wire
# decoding), the wire-contract analyzer suite (HG11xx covers the new
# /subscribe + /notifications envelopes), and the chaos acceptance
# soak (multi-seed differential equality under concurrent ingest,
# 1k-subscription coalescing, door resume across a replica kill) —
# followed by a LIVE smoke: a primary + 2 serving replicas + the front
# door over real HTTP sockets, one subscription placed through the
# door, its owning replica KILLED between deltas, and the next
# long-poll must come back with the synthesized chained resume note —
# no loss, no duplicates, no error.
#
# Sits beside replica.sh (deployment tier), perf.sh (kernels + AOT),
# and lint.sh/verify.sh: this one gates the streaming tier. No
# hgverify/concord refresh is needed here by design — standing queries
# re-fire through the EXISTING bucketed serve lanes (no new jitted
# entries), which is the point.
#
# Usage: tools/sub.sh [extra pytest args]
#   tools/sub.sh -k shed               # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_sub.py \
    tests/test_sub_soak.py \
    tests/test_hglint_wire.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/sub.sh: subscription tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live smoke: a subscription survives its replica over real HTTP ----------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import shutil
import subprocess
import time
import urllib.parse
import urllib.request

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.replica import (
    FrontDoor,
    HTTPBackend,
    ReplicaConfig,
    ReplicaNode,
    RouterConfig,
    SubmitServer,
    frontdoor_server,
    node_server,
    submit_payload,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime
from hypergraphdb_tpu.sub.registry import match_digest


def serve_cfg():
    return ServeConfig(max_linger_s=0.001, prewarm_aot=False)


net = LoopbackNetwork()
gp = hg.HyperGraph()
pp = HyperGraphPeer.loopback(gp, net, identity="primary")
pp.replication.debounce_s = 0.005
pp.start()
hub = int(gp.add("hub"))
spokes = [int(gp.add(f"s{i}")) for i in range(8)]
for i in range(4):
    gp.add_link((hub, spokes[i]), value=f"e{i}")


def replica(ident):
    gr = hg.HyperGraph()
    node = ReplicaNode(
        gr, HyperGraphPeer.loopback(gr, net, identity=ident),
        ReplicaConfig(primary="primary", anti_entropy_interval_s=0.1,
                      serve=serve_cfg()),
    )
    node.start()
    assert node.wait_converged(timeout=60), f"{ident} never converged"
    return node


n1, n2 = replica("r1"), replica("r2")
nodes = {"r1": n1, "r2": n2}
assert pp.replication.flush()
for n in (n1, n2):
    deadline = time.monotonic() + 30
    while transfer.content_digest(gp) != transfer.content_digest(n.graph):
        assert time.monotonic() < deadline, "replica never caught up"
        time.sleep(0.02)


def resolve(graph, value):
    hs = [int(h) for h in graph.find_all(c.AtomValue(value))]
    assert len(hs) == 1
    return hs[0]


# identical replica builds from the same stream => identical handles;
# the wire payload carries raw replica-local handles
anchor = resolve(n1.graph, "hub")
assert anchor == resolve(n2.graph, "hub")


def truth(graph):
    return {int(h) for h in
            graph.find_all(c.Incident(resolve(graph, "hub")))}


# primary serves submits but has NO subscription tier: the failover
# below must adopt on the surviving replica
prt = ServeRuntime(gp, serve_cfg())
s1, s2 = node_server(n1).start(), node_server(n2).start()
servers = {"r1": s1, "r2": s2}
sp = SubmitServer(lambda p: submit_payload(prt, p, 30.0),
                  health=runtime_health(prt)).start()
fd = FrontDoor(
    HTTPBackend("primary", sp.url, role="primary"),
    [HTTPBackend("r1", s1.url), HTTPBackend("r2", s2.url)],
    RouterConfig(breaker_threshold=2, breaker_cooldown_s=3600.0,
                 poll_interval_s=0, health_refresh_s=3600.0),
).start()
fd.refresh_health()
fsrv = frontdoor_server(fd).start()
curl = shutil.which("curl")


def http_json(url, body=None):
    if curl:
        cmd = [curl, "-fsS", "--max-time", "20"]
        if body is not None:
            cmd += ["-H", "Content-Type: application/json", "-d", body]
        out = subprocess.run(cmd + [url], check=True,
                             capture_output=True, text=True)
        return json.loads(out.stdout)
    req = urllib.request.Request(
        url, data=None if body is None else body.encode("utf-8"),
        headers={} if body is None
        else {"Content-Type": "application/json"},
        method="GET" if body is None else "POST",
    )
    with urllib.request.urlopen(req, timeout=20) as r:
        assert r.status == 200
        return json.loads(r.read().decode("utf-8"))


def poll(dsid, timeout_s=2):
    qs = urllib.parse.urlencode(
        {"id": dsid, "timeout_s": timeout_s, "max": 32})
    return http_json(fsrv.url + "/notifications?" + qs)


try:
    # place one standing pattern THROUGH the door
    resp = http_json(fsrv.url + "/subscribe", json.dumps(
        {"what": "subscribe", "kind": "pattern", "anchors": [anchor],
         "window": 64}))
    assert resp["what"] == "subscribed", resp
    dsid, owner = resp["id"], resp["routed_to"]
    assert dsid.startswith("dsub-") and owner in ("r1", "r2"), resp
    matches, seq = set(resp["matches"]), resp["seq"]
    assert matches == truth(n1.graph)

    def fold_until(want, deadline_s=30):
        """Long-poll + fold deltas until the set equals ``want``,
        enforcing chain/no-dup/no-loss/digest on every note."""
        global seq
        deadline = time.monotonic() + deadline_s
        while matches != want:
            assert time.monotonic() < deadline, \
                f"fold never reached truth: {sorted(matches)}"
            env = poll(dsid)
            assert env["what"] == "notifications", env
            for n in env["notes"]:
                assert seq <= n["seq_from"] <= n["seq_to"], n
                added, removed = set(n["added"]), set(n["removed"])
                assert added.isdisjoint(matches), "duplicate delivery"
                assert removed <= matches, "phantom removal"
                matches.difference_update(removed)
                matches.update(added)
                seq = n["seq_to"]
                assert n["digest"] == match_digest(matches), n

    # delta 1 flows through the owner
    gp.add_link((hub, spokes[5]), value="live-1")
    assert pp.replication.flush()
    fold_until(truth(nodes[owner].graph))

    # KILL the owning replica (server and node, no drain — a death),
    # then land ingest it will never see
    survivor = "r2" if owner == "r1" else "r1"
    servers[owner].stop()
    nodes[owner].stop(drain=False)
    gp.add_link((hub, spokes[6]), value="live-2")
    surv = nodes[survivor]
    deadline = time.monotonic() + 30
    while transfer.content_digest(gp) != transfer.content_digest(surv.graph):
        assert time.monotonic() < deadline, "survivor never caught up"
        time.sleep(0.02)

    # the poll crosses the kill: the door re-places the subscription on
    # the survivor and answers with ONE synthesized chained note
    fold_until(truth(surv.graph))
    failovers = fd.metrics.counters.get("router.sub_failovers", 0)
    assert failovers == 1, f"expected 1 failover, saw {failovers}"

    # still live on the survivor after the resume
    gp.add_link((hub, spokes[7]), value="live-3")
    assert pp.replication.flush()
    fold_until(truth(surv.graph))

    print(f"tools/sub.sh smoke: subscription {dsid} through {fsrv.url} "
          f"survived killing {owner}; resumed on {survivor} with the "
          f"synthesized chained note ({'curl' if curl else 'urllib'}), "
          f"{len(matches)} matches, seq {seq}, 0 lost, 0 duplicated")
finally:
    fsrv.stop()
    fd.stop()
    sp.stop()
    for srv in servers.values():
        try:
            srv.stop()       # idempotent for the already-killed owner
        except Exception:
            pass
    prt.close()
    for node in nodes.values():
        try:
            node.stop(drain=False)
        except Exception:
            pass
    pp.stop()
    gp.close()
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/sub.sh: live subscription smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/sub.sh: subscription gate green"
exit 0
