#!/usr/bin/env bash
# hgverify repo gate: traces every registered kernel entry point and fails
# on any jaxpr-contract violation (HV1xx-HV3xx) or static cost drift
# beyond tolerance (HV4xx vs tools/hgverify/costs.json). Tier-1 enforces
# the same checks via tests/test_hgverify.py.
#
# Exit codes: 0 clean · 1 findings · >= 2 analyzer crash / usage error
# (a crash is an infrastructure failure, NOT a finding — CI must fail it
# loudly instead of reporting "1 finding"). Same contract as tools/lint.sh.
#
# The CLI pins the trace environment itself (JAX_PLATFORMS=cpu, 8 forced
# host devices) so the committed costs.json numbers reproduce everywhere.
#
# Usage: tools/verify.sh [extra hgverify args]
#   tools/verify.sh --only HV4          # cost gate only, fast local run
#   tools/verify.sh --update-costs      # accept current costs as budgets
#   tools/verify.sh --concord           # diff ground truth vs hglint
#   tools/verify.sh --output json       # machine-readable CI report
set -uo pipefail
cd "$(dirname "$0")/.."
python -m tools.hgverify "$@"
rc=$?
if [ "$rc" -ge 2 ]; then
    echo "tools/verify.sh: hgverify analyzer crashed (exit $rc);" \
         "fix the analyzer before trusting this gate" >&2
fi
exit "$rc"
