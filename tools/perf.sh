#!/usr/bin/env bash
# Raw-speed gate: the fused-vs-unfused differential suite (fused Pallas
# pull-BFS megakernel == the staged ellbfs chain == the dense serve
# sweep, bit for bit, incl. the delta-overlay path) plus an AOT-cache
# cold/warm smoke over a REAL ServeRuntime — the second process's
# compile of every warmed bucket must be a cache hit.
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth),
# chaos.sh (fault injection), and obs.sh (telemetry): this one gates the
# performance plane's correctness contracts.
#
# Usage: tools/perf.sh [extra pytest args]
#   tools/perf.sh -k fused             # differential suite only
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_pallas_bfs.py \
    tests/test_pallas_gather.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/perf.sh: differential suite failed (exit $rc)" >&2
    exit "$rc"
fi

# -- AOT cold/warm smoke: a fresh process over a populated cache must
#    reach first dispatch with zero compiles of the warmed buckets ------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import subprocess
import sys
import tempfile

cache = tempfile.mkdtemp(prefix="hg_perf_aot_")
code = f"""
import json
import numpy as np
from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = HyperGraph()
nodes = list(g.add_nodes_bulk([f"n{{i}}" for i in range(60)]))
r = np.random.default_rng(0)
for i in range(120):
    ts = r.choice(nodes, size=2, replace=False)
    g.add_link([int(t) for t in ts], value=i)
rt = ServeRuntime(g, ServeConfig(buckets=(4, 8), max_linger_s=0.001,
                                 top_r=8, aot_cache_dir={cache!r}))
res = rt.submit_bfs(int(nodes[0]), max_hops=2).result(timeout=120)
print("AOT " + json.dumps(rt.stats_snapshot()["aot"]))
rt.close()
g.close()
"""

def run():
    import json
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("AOT "):
            return json.loads(line[4:])
    raise SystemExit(f"aot smoke subprocess failed (rc={proc.returncode}):"
                     f"\n{proc.stderr[-2000:]}")

import shutil

try:
    cold = run()
    warm = run()
finally:
    shutil.rmtree(cache, ignore_errors=True)  # multi-MB executables
assert cold["misses"] >= 2 and cold["puts"] >= 2, f"cold never compiled: {cold}"
assert warm["misses"] == 0, f"warm process recompiled: {warm}"
assert warm["disk_hits"] >= 2, f"warm process missed the disk cache: {warm}"
print(f"tools/perf.sh smoke: cold compiled {cold['misses']} buckets "
      f"({cold['compile_s']}s), warm process hit {warm['disk_hits']} from "
      f"disk with zero compiles — AOT cache OK")
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/perf.sh: AOT cold/warm smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/perf.sh: perf gate green"
exit 0
