#!/usr/bin/env bash
# Performance-plane gate: the fused-vs-unfused differential suite (fused
# Pallas pull-BFS megakernel == the staged ellbfs chain == the dense
# serve sweep, bit for bit, incl. the delta-overlay path), the hgperf
# suites (runtime perf sentinel + bench envelope/diff), an AOT-cache
# cold/warm smoke over a REAL ServeRuntime, the bench --diff live gate
# (a recorded c6 mini-run diffs clean against itself; the committed
# injected-regression fixture pair must exit nonzero), and a live
# sentinel drill (seeded serve.launch slowdown on a real runtime fires
# exactly one incident with the flight window + profiler capture on
# disk).
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth),
# chaos.sh (fault injection), and obs.sh (telemetry): this one gates the
# performance plane's correctness contracts.
#
# Usage: tools/perf.sh [extra pytest args]
#   tools/perf.sh -k fused             # differential suite only
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_pallas_bfs.py \
    tests/test_pallas_gather.py \
    tests/test_perf_sentinel.py \
    tests/test_bench_envelope.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/perf.sh: differential/perf suites failed (exit $rc)" >&2
    exit "$rc"
fi

# -- AOT cold/warm smoke: a fresh process over a populated cache must
#    reach first dispatch with zero compiles of the warmed buckets ------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import subprocess
import sys
import tempfile

cache = tempfile.mkdtemp(prefix="hg_perf_aot_")
code = f"""
import json
import numpy as np
from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

g = HyperGraph()
nodes = list(g.add_nodes_bulk([f"n{{i}}" for i in range(60)]))
r = np.random.default_rng(0)
for i in range(120):
    ts = r.choice(nodes, size=2, replace=False)
    g.add_link([int(t) for t in ts], value=i)
rt = ServeRuntime(g, ServeConfig(buckets=(4, 8), max_linger_s=0.001,
                                 top_r=8, aot_cache_dir={cache!r}))
res = rt.submit_bfs(int(nodes[0]), max_hops=2).result(timeout=120)
print("AOT " + json.dumps(rt.stats_snapshot()["aot"]))
rt.close()
g.close()
"""

def run():
    import json
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=600)
    for line in proc.stdout.splitlines():
        if line.startswith("AOT "):
            return json.loads(line[4:])
    raise SystemExit(f"aot smoke subprocess failed (rc={proc.returncode}):"
                     f"\n{proc.stderr[-2000:]}")

import shutil

try:
    cold = run()
    warm = run()
finally:
    shutil.rmtree(cache, ignore_errors=True)  # multi-MB executables
assert cold["misses"] >= 2 and cold["puts"] >= 2, f"cold never compiled: {cold}"
assert warm["misses"] == 0, f"warm process recompiled: {warm}"
assert warm["disk_hits"] >= 2, f"warm process missed the disk cache: {warm}"
print(f"tools/perf.sh smoke: cold compiled {cold['misses']} buckets "
      f"({cold['compile_s']}s), warm process hit {warm['disk_hits']} from "
      f"disk with zero compiles — AOT cache OK")
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/perf.sh: AOT cold/warm smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi

# -- bench --diff live gate: record a c6 mini-run, diff it against itself
#    (identical files MUST exit 0), then the committed injected-regression
#    fixture pair MUST exit nonzero — the contract the real-TPU sweep and
#    CI both lean on ----------------------------------------------------------
DIFF_TMP="$(mktemp -d -t hg_perf_diff_XXXXXX)"
trap 'rm -rf "$DIFF_TMP"' EXIT
BENCH_RECORD_DIR="$DIFF_TMP" BENCH_C6_TAG=perfgate \
BENCH_C6_ENTITIES=2000 BENCH_C6_LINKS=4000 BENCH_C6_REQUESTS=64 \
BENCH_C6_BASELINE_N=16 BENCH_C6_COLD=0 \
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" \
    python -c "import bench; bench._config_c6()" >/dev/null
rc=$?
if [ "$rc" -ne 0 ] || [ ! -f "$DIFF_TMP/BENCH_C6_perfgate.json" ]; then
    echo "tools/perf.sh: c6 mini-run failed to record (exit $rc)" >&2
    exit 1
fi
python bench.py --diff "$DIFF_TMP/BENCH_C6_perfgate.json" \
    "$DIFF_TMP/BENCH_C6_perfgate.json" >/dev/null
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/perf.sh: --diff of identical recordings exited $rc (want 0)" >&2
    exit 1
fi
python bench.py --diff tests/perf_fixtures/BENCH_C6_base.json \
    tests/perf_fixtures/BENCH_C6_regressed.json >/dev/null
rc=$?
if [ "$rc" -ne 1 ]; then
    echo "tools/perf.sh: --diff of regression fixtures exited $rc (want 1)" >&2
    exit 1
fi
echo "tools/perf.sh: bench --diff gate OK (self-diff clean, injected regression caught)"

# -- live sentinel drill: a REAL runtime with a seeded serve.launch
#    slowdown (sleeping when= hook — latency injection, zero errors) must
#    fire exactly ONE perf_drift incident, with the flight window dump and
#    the bounded profiler capture in the incident dir -------------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import os
import tempfile
import time

import numpy as np

from hypergraphdb_tpu import HyperGraph
from hypergraphdb_tpu.fault import global_faults
from hypergraphdb_tpu.obs.flight import FlightRecorder
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.obs.perf import PerfSentinel
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

incident_dir = tempfile.mkdtemp(prefix="hg_perf_drill_")
flight = FlightRecorder(incident_dir=incident_dir, min_dump_interval_s=0.0)
sentinel = PerfSentinel(baseline={"lanes": {}}, flight=flight,
                        windows=(2.0, 6.0), min_samples=4,
                        eval_interval_s=0.0, profile_s=1.0)

g = HyperGraph()
nodes = list(g.add_nodes_bulk([f"n{i}" for i in range(80)]))
r = np.random.default_rng(0)
for i in range(160):
    ts = r.choice(nodes, size=2, replace=False)
    g.add_link([int(t) for t in ts], value=i)
rt = ServeRuntime(g, ServeConfig(buckets=(4,), max_linger_s=0.001,
                                 top_r=16, perf=sentinel))

def soak(n):
    for i in range(n):
        rt.submit_bfs(int(nodes[i % len(nodes)]), max_hops=2).result(
            timeout=120)

soak(4)          # warmup: compiles must not pollute the healthy digest
time.sleep(2.1)  # ... so let it age out of the short measurement window
soak(24)         # healthy phase
healthy = sentinel.snapshot()["lanes"]["bfs"]["windows"][0]
assert healthy["n"] >= 4 and flight.incidents == 0, healthy
# commit the measured healthy window as the baseline contract
# (floor-clamped so CI scheduling hiccups sit inside the limits; the
# 0.15 s injection breaches 3x either floor with a wide margin), then
# inject: a sleeping when= hook on the serve.launch fault point — pure
# latency, no errors fire (the hook always declines the schedule)
sentinel.baseline["lanes"]["bfs"] = {
    "p50_s": max(healthy["p50_s"], 0.01),
    "p99_s": max(healthy["p99_s"], 0.02),
}
faults = global_faults()
faults.enable(seed=0)
def slow(ctx):
    time.sleep(0.15)
    return False
faults.arm("serve.launch", prob=0.0, when=slow)  # never fires, only sleeps
try:
    soak(24)  # the seeded slowdown (~3.6 s: fills both drift windows)
finally:
    faults.disarm("serve.launch")
    faults.disable()
assert flight.incidents == 1, f"want exactly 1 incident, got {flight.incidents}"
lane = sentinel.snapshot()["lanes"]["bfs"]
assert lane["violating"] is True
perf_health = runtime_health(rt)()[1]["perf"]
assert perf_health["violating"] == ["bfs"], perf_health
sentinel.close()
rt.close(); g.close()
dump, profile_dir = lane["last_incident"], lane["last_profile"]
assert dump and os.path.exists(dump), "flight window dump missing"
assert profile_dir and os.path.isdir(profile_dir), "profile dir missing"
manifest = json.load(open(os.path.join(profile_dir, "PROFILE.json")))
assert manifest["lane"] == "bfs" and "t1" in manifest, manifest
extra = [f for f in os.listdir(profile_dir) if f != "PROFILE.json"]
if manifest["profiler_active"]:
    assert extra, "active profiler session left no trace files"
import shutil
shutil.rmtree(incident_dir, ignore_errors=True)
print(f"tools/perf.sh drill: 1 incident, flight dump + profile capture "
      f"(profiler_active={manifest['profiler_active']}, "
      f"trace_files={len(extra)}) — sentinel OK")
PY
drill_rc=$?
if [ "$drill_rc" -ne 0 ]; then
    echo "tools/perf.sh: live sentinel drill failed (exit $drill_rc)" >&2
    exit "$drill_rc"
fi
echo "tools/perf.sh: perf gate green"
exit 0
