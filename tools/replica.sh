#!/usr/bin/env bash
# hgreplica gate: the replicated-serving-tier suite — replica node
# lifecycle (bootstrap→follow→serve, the lag gate), front-door placement
# + breaker failover, gap-aware replication convergence (contiguity
# tracking, anti-entropy, the redelivery journal), and the chunk-boundary
# crash recovery drill — followed by a LIVE smoke: a primary + 2 serving
# replicas + the front door over real HTTP sockets, one replica killed
# mid-scrape, and every submit through the door must come back 200
# (curl -f when present, stdlib urllib otherwise — degraded, never down).
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth),
# chaos.sh (fault injection), obs.sh (telemetry), and perf.sh (fused
# kernel + AOT): this one gates the deployment tier.
#
# Usage: tools/replica.sh [extra pytest args]
#   tools/replica.sh -k router         # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_replication_gaps.py \
    tests/test_replica.py \
    tests/test_replica_router.py \
    tests/test_replica_recovery.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/replica.sh: replica tests failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live smoke: the whole tier over real sockets ----------------------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import shutil
import subprocess
import urllib.request

import hypergraphdb_tpu as hg
from hypergraphdb_tpu.obs.http import runtime_health
from hypergraphdb_tpu.peer import transfer
from hypergraphdb_tpu.peer.peer import HyperGraphPeer
from hypergraphdb_tpu.peer.transport import LoopbackNetwork
from hypergraphdb_tpu.replica import (
    FrontDoor,
    HTTPBackend,
    ReplicaConfig,
    ReplicaNode,
    RouterConfig,
    SubmitServer,
    frontdoor_server,
    node_server,
    submit_payload,
)
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime


def serve_cfg():
    return ServeConfig(max_linger_s=0.001, top_r=8, prewarm_aot=False)


net = LoopbackNetwork()
gp = hg.HyperGraph()
pp = HyperGraphPeer.loopback(gp, net, identity="primary")
pp.replication.debounce_s = 0.005
pp.start()
hs = [int(gp.add(f"s{i}")) for i in range(10)]
for i in range(9):
    gp.add_link([hs[i], hs[i + 1]], value=f"e{i}")


def replica(ident):
    gr = hg.HyperGraph()
    node = ReplicaNode(
        gr, HyperGraphPeer.loopback(gr, net, identity=ident),
        ReplicaConfig(primary="primary", serve=serve_cfg()),
    )
    node.start()
    assert node.wait_converged(timeout=60), f"{ident} never converged"
    return node


n1, n2 = replica("r1"), replica("r2")
prt = ServeRuntime(gp, serve_cfg())
s1, s2 = node_server(n1).start(), node_server(n2).start()
sp = SubmitServer(lambda p: submit_payload(prt, p, 30.0),
                  health=runtime_health(prt)).start()
fd = FrontDoor(
    HTTPBackend("primary", sp.url, role="primary"),
    [HTTPBackend("r1", s1.url), HTTPBackend("r2", s2.url)],
    RouterConfig(breaker_threshold=2, breaker_cooldown_s=5.0,
                 poll_interval_s=0.1),
).start()
fsrv = frontdoor_server(fd).start()

gid = transfer.gid_of(gp, hs[0], "primary")
body = json.dumps({"kind": "bfs", "seed_gid": gid, "max_hops": 2,
                   "deadline_s": 10.0})
curl = shutil.which("curl")


def post():
    """One submit through the front door; raises on any non-200."""
    url = fsrv.url + "/submit"
    if curl:
        out = subprocess.run(
            [curl, "-fsS", "--max-time", "15",
             "-H", "Content-Type: application/json", "-d", body, url],
            check=True, capture_output=True, text=True,
        )
        return json.loads(out.stdout)
    req = urllib.request.Request(
        url, data=body.encode("utf-8"),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    with urllib.request.urlopen(req, timeout=15) as r:
        assert r.status == 200
        return json.loads(r.read().decode("utf-8"))


def get_healthz():
    url = fsrv.url + "/healthz"
    if curl:
        out = subprocess.run([curl, "-fsS", "--max-time", "10", url],
                             check=True, capture_output=True, text=True)
        return json.loads(out.stdout)
    with urllib.request.urlopen(url, timeout=10) as r:
        assert r.status == 200
        return json.loads(r.read().decode("utf-8"))


try:
    routed = []
    for _ in range(6):                      # healthy tier: reads spread
        routed.append(post()["routed_to"])
    assert set(routed) <= {"r1", "r2"}, routed
    # KILL r2 mid-scrape (server and node, no drain — a death)
    s2.stop()
    n2.stop(drain=False)
    for _ in range(8):                      # every one still 200
        routed.append(post()["routed_to"])
    assert "r2" not in routed[6:], routed
    assert set(routed[6:]) <= {"r1", "primary"}, routed
    health = get_healthz()                  # the door itself stays 200
    assert health["role"] == "router" and "backends" in health, health
    print(f"tools/replica.sh smoke: {len(routed)} submits through "
          f"{fsrv.url} all 200 ({'curl' if curl else 'urllib'}); "
          f"r2 killed mid-scrape, routed_to={routed}")
finally:
    fsrv.stop()
    fd.stop()
    sp.stop()
    s1.stop()
    prt.close()
    n1.stop()
    pp.stop()
    gp.close()
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/replica.sh: live failover smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/replica.sh: replica gate green"
exit 0
