"""Rule registry and finding model for hgverify.

Mirrors ``tools/hglint/model.py`` — same finding fields, same
``report_version`` 2 report shape — but the rules verify the **traced
jaxpr/HLO**, not the AST: hgverify findings are ground truth for what XLA
will actually execute, where hglint findings are predictions.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field

SEVERITIES = ("error", "warning", "info")

#: one-line summaries, keyed by rule id (also the rule registry)
RULES = {
    # -- family 1: traced-graph purity (the jaxpr itself) ---------------------
    "HV100": "registered entry point failed to trace/lower",
    "HV101": "pure_callback inside the traced graph (host round-trip per "
             "dispatch)",
    "HV102": "io_callback inside the traced graph (ordered host side "
             "effect per dispatch)",
    "HV103": "debug_callback/debug.print inside the traced graph",
    "HV104": "legacy host_callback primitive inside the traced graph",
    # -- family 2: collective/mesh consistency --------------------------------
    "HV201": "collective axis name absent from the entry's declared mesh",
    "HV202": "cond/switch branches carry mismatched collectives",
    "HV203": "traced graph issues collectives but the entry declares no "
             "mesh",
    # -- family 3: donation contracts -----------------------------------------
    "HV301": "donated buffer matches no output (donation silently dropped)",
    "HV302": "donated input aliased into more than one output",
    "HV303": "entry declares donation but the traced jit donates nothing",
    # -- family 4: static cost budgets ----------------------------------------
    "HV401": "entry cost metric drifted beyond tolerance vs costs.json",
    "HV402": "entry has no budget in costs.json (uncovered)",
    "HV403": "stale costs.json entry with no live entry point",
}

RULE_SEVERITY = {
    "HV100": "error",
    "HV101": "error",
    "HV102": "error",
    "HV103": "warning",
    "HV104": "error",
    "HV201": "error",
    "HV202": "error",
    "HV203": "error",
    "HV301": "warning",
    "HV302": "error",
    "HV303": "error",
    "HV401": "error",
    "HV402": "warning",
    "HV403": "error",
}

#: family prefix -> README.md section anchor
DOC_ANCHORS = {
    "HV1": "hv1xx-traced-graph-purity",
    "HV2": "hv2xx-collective-mesh-consistency",
    "HV3": "hv3xx-donation-contracts",
    "HV4": "hv4xx-static-cost-budgets",
}


def doc_anchor(rule: str) -> str:
    slug = DOC_ANCHORS.get(rule[:3], "jaxpr-verification-hgverify")
    return f"README.md#{slug}"


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str           # source file of the entry point
    line: int           # entry definition line
    message: str
    scope: str = "<entry>"    # registered entry name
    severity: str = field(default="")

    def __post_init__(self):
        if not self.severity:
            object.__setattr__(
                self, "severity", RULE_SEVERITY.get(self.rule, "warning")
            )

    @property
    def baseline_key(self) -> str:
        return f"{self.rule}:{_norm(self.path)}:{self.scope}"

    def render(self) -> str:
        return (
            f"{self.path}:{self.line} {self.rule} {self.severity} "
            f"[{self.scope}]: {self.message} [{doc_anchor(self.rule)}]"
        )


def _norm(path: str) -> str:
    return path.replace(os.sep, "/")


def sort_findings(findings):
    sev_rank = {s: i for i, s in enumerate(SEVERITIES)}
    return sorted(
        findings,
        key=lambda f: (f.scope, sev_rank.get(f.severity, 9), f.rule, f.line),
    )


def parse_only(only) -> tuple:
    """``--only`` prefixes with typo rejection (same contract as hglint:
    a prefix matching no rule raises instead of going silently green)."""
    if not only:
        return ()
    if isinstance(only, str):
        only = only.split(",")
    prefixes = tuple(p.strip() for p in only if p and p.strip())
    for p in prefixes:
        if not any(r.startswith(p) for r in RULES):
            raise ValueError(
                f"--only prefix {p!r} matches no known rule; valid ids are "
                f"{sorted(RULES)} (prefixes like 'HV4' select a family)"
            )
    return prefixes
