"""Entry-point harvesting: trace and lower every registered entry.

The registry lives in ``hypergraphdb_tpu.verify`` (the product side, so
kernel modules can decorate without depending on the tools tree); this
module imports the kernel modules — which populates the registry as a
side effect — then traces each entry's exemplar args to a closed jaxpr
and compiles it on the CPU backend for XLA's static cost analysis.

Everything runs under ``JAX_PLATFORMS=cpu``: tracing is
platform-independent (the jaxpr IS the ground truth of what a TPU run
would execute), and the CPU cost model, while not TPU-accurate in
absolute terms, is deterministic — exactly what a *regression* gate
needs.
"""

from __future__ import annotations

import os
import warnings
from dataclasses import dataclass
from typing import Optional

#: kernel modules whose import populates the production registry
PRODUCT_MODULES = (
    "hypergraphdb_tpu.ops.frontier",
    "hypergraphdb_tpu.ops.bitfrontier",
    "hypergraphdb_tpu.ops.ellbfs",
    "hypergraphdb_tpu.ops.setops",
    "hypergraphdb_tpu.ops.pallas_gather",
    "hypergraphdb_tpu.ops.pallas_bfs",
    "hypergraphdb_tpu.ops.incremental",
    "hypergraphdb_tpu.ops.serving",
    "hypergraphdb_tpu.ops.join",
    "hypergraphdb_tpu.ops.sharded_serving",
    "hypergraphdb_tpu.ops.value_index",
    "hypergraphdb_tpu.parallel.sharded",
)

#: cost metrics the budget gate tracks, in report order
COST_METRICS = ("flops", "bytes_accessed", "temp_bytes")


@dataclass
class Trace:
    """One harvested entry: the traced jaxpr + measured static costs, or
    the error that prevented either (an HV100 finding downstream)."""

    entry: object                  # verify.Entry
    jaxpr: Optional[object] = None     # jax.core.ClosedJaxpr
    costs: Optional[dict] = None       # metric -> number
    error: Optional[str] = None        # trace/lower failure summary

    @property
    def ok(self) -> bool:
        return self.jaxpr is not None


def production_registry():
    """Import the kernel modules and return the populated registry."""
    import importlib

    for name in PRODUCT_MODULES:
        importlib.import_module(name)
    from hypergraphdb_tpu.verify import REGISTRY

    return REGISTRY


def harvest(registry) -> list:
    """Trace + cost-compile every entry in ``registry``; never raises for
    a single bad entry — failures surface as ``Trace.error``."""
    return [trace_entry(e) for e in registry]


def _split_exemplars(raw) -> tuple:
    """A ``shapes=`` callable returns either a tuple of positional
    exemplars, or an ``(args_tuple, kwargs_dict)`` pair for entries whose
    traced arguments sit after static positional parameters."""
    if (isinstance(raw, tuple) and len(raw) == 2
            and isinstance(raw[0], (tuple, list))
            and isinstance(raw[1], dict)):
        return tuple(raw[0]), dict(raw[1])
    return tuple(raw), {}


def _bind(entry, n_pos: int, kw_names: list):
    """Flatten (positional + keyword) exemplars into one positional
    signature so every exemplar is a traced INPUT (a partial-bound
    ShapeDtypeStruct would leak into the trace as a closure constant);
    static kwargs stay concrete Python values."""
    fn, statics = entry.fn, entry.statics

    def bound(*flat):
        kws = dict(zip(kw_names, flat[n_pos:]))
        return fn(*flat[:n_pos], **kws, **statics)

    return bound


def trace_entry(entry) -> Trace:
    import jax

    try:
        args, kwargs = _split_exemplars(entry.shapes())
        kw_names = list(kwargs)
        flat = args + tuple(kwargs[k] for k in kw_names)
        bound = _bind(entry, len(args), kw_names)
        # ONE trace serves both consumers: ``traced.jaxpr`` for the
        # HV1xx-HV3xx walks (inner pjit eqns keep their donated_invars)
        # and ``traced.lower()`` for the cost analysis
        traced = jax.jit(bound).trace(*flat)
        jaxpr = traced.jaxpr
    except Exception as exc:  # noqa: BLE001 - reported as HV100
        return Trace(entry=entry, error=_summ(exc))
    costs = None
    cost_err = None
    try:
        costs = measure_costs(traced)
    except Exception as exc:  # noqa: BLE001 - reported as HV100
        cost_err = _summ(exc)
    return Trace(entry=entry, jaxpr=jaxpr, costs=costs, error=cost_err)


def measure_costs(traced) -> dict:
    """Compile the traced entry on the current (CPU) backend and read
    XLA's static cost analysis: FLOPs, bytes accessed, and the peak
    temp-buffer footprint from the memory analysis."""
    with warnings.catch_warnings():
        # CPU drops donation with a warning; that is HV3xx's job to judge
        warnings.simplefilter("ignore")
        compiled = traced.lower().compile()
    ca = compiled.cost_analysis()
    props = ca[0] if isinstance(ca, (list, tuple)) else (ca or {})
    mem = compiled.memory_analysis()
    return {
        "flops": int(props.get("flops", 0) or 0),
        "bytes_accessed": int(props.get("bytes accessed", 0) or 0),
        "temp_bytes": int(getattr(mem, "temp_size_in_bytes", 0) or 0),
    }


def _summ(exc: Exception) -> str:
    s = f"{type(exc).__name__}: {exc}"
    first = s.splitlines()[0] if s else type(exc).__name__
    return first[:300]


def rel_path(path: str) -> str:
    try:
        rel = os.path.relpath(path)
    except ValueError:  # pragma: no cover - different drive on windows
        return path
    return path if rel.startswith("..") else rel
