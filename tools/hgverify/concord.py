"""``--concord``: diff jaxpr ground truth against hglint's AST layer.

hglint predicts hazards from syntax; hgverify observes them in the traced
IR. Running both over the same entry points measures each layer's blind
spots (the EmptyHeaded move: validate plans at the IR level, then use the
disagreement to sharpen the cheap layer):

- ``hglint_false_negative`` — the jaxpr shows a hazard the AST layer
  missed on that entry's module (a callback laundered through a helper,
  a computed axis name, donation dropped by a wrapper);
- ``hglint_only`` — the AST layer flags the module but the traced entry
  is clean: either an hglint false positive, or a hazard on a code path
  the exemplar does not exercise (both worth knowing);
- ``agree_flagged`` / ``agree_clean`` — the layers corroborate.

Comparison is at module granularity (hglint findings in the entry's
source file vs hgverify findings on the entry), per comparable family:
HV1xx ↔ HG1xx host sync, HV2xx ↔ HG6xx collectives, HV3xx ↔ HG106
donation. HV4xx has no AST counterpart — cost is only visible in the IR.
"""

from __future__ import annotations

from tools.hgverify.harvest import rel_path

#: hgverify family prefix -> predicate over hglint rule ids
FAMILY_MAP = {
    "HV1": lambda r: r.startswith("HG1") and r != "HG106",
    "HV2": lambda r: r.startswith("HG6"),
    "HV3": lambda r: r == "HG106",
}


def concord(traces: list, verify_findings: list, paths: list) -> dict:
    """Run hglint over ``paths`` and cross-tabulate with hgverify
    findings per (entry, family). Returns the machine-readable table
    embedded in the ``--output json`` report."""
    from tools.hglint import engine as hglint_engine

    lint = hglint_engine.run_lint(list(paths))
    lint_by_path: dict = {}
    for f in lint:
        lint_by_path.setdefault(f.path.replace("\\", "/"), []).append(f)

    vf_by_entry: dict = {}
    for f in verify_findings:
        vf_by_entry.setdefault(f.scope, []).append(f)

    rows = []
    for tr in traces:
        entry = tr.entry
        epath = rel_path(entry.path).replace("\\", "/")
        module_lint = lint_by_path.get(epath, [])
        entry_verify = vf_by_entry.get(entry.name, [])
        for hv_prefix, hg_pred in sorted(FAMILY_MAP.items()):
            v_rules = sorted({
                f.rule for f in entry_verify
                if f.rule.startswith(hv_prefix) and f.rule != "HV100"
            })
            l_rules = sorted({
                f.rule for f in module_lint if hg_pred(f.rule)
            })
            if v_rules and l_rules:
                verdict = "agree_flagged"
            elif v_rules:
                verdict = "hglint_false_negative"
            elif l_rules:
                verdict = "hglint_only"
            else:
                verdict = "agree_clean"
            rows.append({
                "entry": entry.name,
                "family": hv_prefix + "xx",
                "hgverify": v_rules,
                "hglint": l_rules,
                "verdict": verdict,
            })
    summary = {}
    for row in rows:
        summary[row["verdict"]] = summary.get(row["verdict"], 0) + 1
    return {
        "paths": list(paths),
        "hglint_findings": len(lint),
        "rows": rows,
        "summary": summary,
    }


def render(table: dict) -> str:
    lines = [
        "hgverify concordance (jaxpr ground truth vs hglint AST "
        f"predictions over {', '.join(table['paths'])}):"
    ]
    interesting = [r for r in table["rows"]
                   if r["verdict"] != "agree_clean"]
    for row in interesting:
        lines.append(
            f"  {row['entry']:<44} {row['family']}: "
            f"hgverify={','.join(row['hgverify']) or '-'} "
            f"hglint={','.join(row['hglint']) or '-'} -> {row['verdict']}"
        )
    if not interesting:
        lines.append("  all (entry, family) pairs agree clean")
    s = table["summary"]
    lines.append(
        "  summary: " + ", ".join(f"{k}={v}" for k, v in sorted(s.items()))
    )
    return "\n".join(lines)
