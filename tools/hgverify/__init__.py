"""hgverify — jaxpr-level contract verification + static cost gate.

Where ``tools.hglint`` predicts TPU hazards from the AST, hgverify
*traces* the registered kernel entry points (``hypergraphdb_tpu.verify``
registry, populated by ``@hgverify.entry`` decorators at the kernel
definitions) and verifies the closed jaxpr / compiled HLO itself:

- **HV1xx** traced-graph purity: no ``pure_callback`` / ``io_callback``
  / ``debug_callback`` / legacy host_callback primitives in the graph;
- **HV2xx** collective consistency: collective axis names match the
  entry's declared deployment mesh; ``cond`` branches carry identical
  collective sequences;
- **HV3xx** donation contracts: declared donations exist in the traced
  jit, match an output buffer, and never alias two outputs;
- **HV4xx** static cost budgets: FLOPs / bytes accessed / peak temp size
  vs ``tools/hgverify/costs.json`` within ±15% (``--update-costs`` to
  accept changes).

CLI: ``python -m tools.hgverify`` · gate: ``tools/verify.sh`` ·
concordance vs hglint: ``--concord``.
"""

from hypergraphdb_tpu.verify import REGISTRY, Registry, entry  # noqa: F401

from tools.hgverify.costs import (  # noqa: F401
    DEFAULT_COSTS_PATH,
    DEFAULT_TOLERANCE,
    load_costs,
    write_costs,
)
from tools.hgverify.engine import build_report, run_verify  # noqa: F401
from tools.hgverify.harvest import harvest, trace_entry  # noqa: F401
from tools.hgverify.model import (  # noqa: F401
    RULES,
    Finding,
    doc_anchor,
    parse_only,
    sort_findings,
)
