"""HV1xx–HV3xx: contract checks over the traced closed jaxpr.

These are the IR-level twins of hglint's AST predictions (HG1xx host
sync, HG6xx collectives, HG106 donation): instead of guessing from
syntax, they walk the equations tracing actually produced — through
``pjit``/``cond``/``scan``/``while``/``shard_map`` sub-jaxprs — so a
callback smuggled in through five layers of helpers, or a collective
whose axis name was computed, is found exactly where XLA will run it.
"""

from __future__ import annotations

from collections import Counter

from tools.hgverify.harvest import Trace, rel_path
from tools.hgverify.model import Finding

#: callback primitive name -> (rule, one-line hazard)
CALLBACK_PRIMS = {
    "pure_callback": ("HV101", "a host round-trip per dispatch"),
    "io_callback": ("HV102", "an ordered host side effect per dispatch"),
    "debug_callback": ("HV103", "host debug callback baked into the "
                                "compiled graph"),
    "outside_call": ("HV104", "legacy host_callback staging"),
    "host_callback_call": ("HV104", "legacy host_callback staging"),
}

#: primitives that communicate across a named mesh axis (axis names live
#: in the ``axes``/``axis_name``/``axis_index_groups`` params)
COLLECTIVE_PRIMS = {
    "psum", "pmax", "pmin", "pmean", "all_gather", "all_to_all",
    "reduce_scatter", "psum_scatter", "ppermute", "pgather",
}

#: device-local mesh queries: they carry an axis name but move no data
NON_COMMUNICATING = {"axis_index"}


def check(traces: list) -> list:
    findings = []
    for tr in traces:
        findings += check_trace(tr)
    return findings


def check_trace(tr: Trace) -> list:
    entry = tr.entry
    path, line, scope = rel_path(entry.path), entry.line, entry.name
    if not tr.ok:
        return [Finding(
            rule="HV100", path=path, line=line, scope=scope,
            message=(f"entry failed to trace/lower with its registered "
                     f"exemplars: {tr.error}"),
        )]
    findings = []
    if tr.error:  # traced, but cost lowering failed
        findings.append(Finding(
            rule="HV100", path=path, line=line, scope=scope,
            message=f"entry traced but failed to compile for cost "
                    f"analysis: {tr.error}",
        ))
    walk = JaxprWalk(tr.jaxpr)
    findings += _check_callbacks(walk, path, line, scope)
    findings += _check_collectives(walk, entry, path, line, scope)
    findings += _check_donation(walk, entry, path, line, scope)
    return findings


# ------------------------------------------------------------------- walker


class JaxprWalk:
    """One recursive pass collecting everything the rules need: callback
    equations, collective equations with their axis names, ``cond`` /
    ``switch`` equations (for branch comparison), and ``pjit`` equations
    carrying donation metadata."""

    def __init__(self, closed):
        self.callbacks: list = []      # (prim_name, depth)
        self.collectives: list = []    # (prim_name, axes tuple)
        self.conds: list = []          # eqn
        self.pjits: list = []          # (eqn, containing jaxpr)
        self.shard_meshes: list = []   # tuple of axis names per shard_map
        self._walk(closed.jaxpr, 0)

    def _walk(self, jaxpr, depth):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in CALLBACK_PRIMS:
                self.callbacks.append((name, depth))
            if name in COLLECTIVE_PRIMS:
                self.collectives.append((name, _axes_of(eqn)))
            if name == "cond":
                self.conds.append(eqn)
            if name == "pjit":
                self.pjits.append((eqn, jaxpr))
            if name == "shard_map":
                mesh = eqn.params.get("mesh")
                axes = tuple(getattr(mesh, "axis_names", ()) or ())
                if axes:
                    self.shard_meshes.append(axes)
            for sub in _sub_jaxprs(eqn):
                self._walk(sub, depth + 1)


def _sub_jaxprs(eqn):
    import jax

    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for w in vs:
            if isinstance(w, jax.core.ClosedJaxpr):
                yield w.jaxpr
            elif isinstance(w, jax.core.Jaxpr):
                yield w


def _axes_of(eqn) -> tuple:
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", None)
    if axes is None:
        return ()
    if isinstance(axes, (tuple, list)):
        return tuple(a for a in axes if isinstance(a, str))
    return (axes,) if isinstance(axes, str) else ()


# ------------------------------------------------------------------- HV1xx


def _check_callbacks(walk: JaxprWalk, path, line, scope) -> list:
    findings = []
    seen = Counter(name for name, _ in walk.callbacks)
    for prim, n in sorted(seen.items()):
        rule, hazard = CALLBACK_PRIMS[prim]
        findings.append(Finding(
            rule=rule, path=path, line=line, scope=scope,
            message=(f"traced graph contains {n}x `{prim}` — {hazard}; "
                     f"hoist the host work out of the jitted region"),
        ))
    return findings


# ------------------------------------------------------------------- HV2xx


def _branch_collectives(branch) -> tuple:
    """Sorted multiset of (collective, axes) inside one cond branch."""
    sub = JaxprWalk(branch)
    return tuple(sorted(
        (name, axes) for name, axes in sub.collectives
        if name not in NON_COMMUNICATING
    ))


def _check_collectives(walk: JaxprWalk, entry, path, line, scope) -> list:
    findings = []
    comm = [(n, a) for n, a in walk.collectives
            if n not in NON_COMMUNICATING]
    used_axes = sorted({ax for _, axes in walk.collectives for ax in axes}
                       | {ax for axes in walk.shard_meshes for ax in axes})
    if entry.mesh is not None:
        declared = set(entry.mesh)
        ghost = [ax for ax in used_axes if ax not in declared]
        if ghost:
            findings.append(Finding(
                rule="HV201", path=path, line=line, scope=scope,
                message=(
                    f"traced collectives/meshes use axis "
                    f"{sorted(set(ghost))} but the entry declares mesh "
                    f"axes {sorted(declared)} — on the deployment mesh "
                    f"these collectives target a nonexistent axis"
                ),
            ))
    elif comm or walk.shard_meshes:
        what = sorted({n for n, _ in comm}) or ["shard_map"]
        findings.append(Finding(
            rule="HV203", path=path, line=line, scope=scope,
            message=(
                f"traced graph issues {what} over axes {used_axes} but "
                f"the entry is registered without a mesh= declaration — "
                f"declare the deployment mesh so axis names are checked"
            ),
        ))
    for eqn in walk.conds:
        branches = eqn.params.get("branches", ())
        sets = [_branch_collectives(b) for b in branches]
        if len({s for s in sets}) > 1:
            desc = " vs ".join(
                "[" + ", ".join(f"{n}{list(a)}" for n, a in s) + "]"
                for s in sets
            )
            findings.append(Finding(
                rule="HV202", path=path, line=line, scope=scope,
                message=(
                    f"cond/switch branches carry mismatched collectives "
                    f"({desc}) — devices taking different branches issue "
                    f"different collective sequences and the mesh "
                    f"deadlocks"
                ),
            ))
    return findings


# ------------------------------------------------------------------- HV3xx


def _check_donation(walk: JaxprWalk, entry, path, line, scope) -> list:
    findings = []
    donated_any = False
    for eqn, containing in walk.pjits:
        donated = eqn.params.get("donated_invars", ())
        if not any(donated):
            continue
        donated_any = True
        inner = eqn.params.get("jaxpr")
        if inner is None:
            continue
        # an input returned unchanged is pruned from the pjit body and
        # passed through in the CONTAINING jaxpr — aliasing shows there
        passthrough = Counter(id(v) for v in containing.outvars)
        out_avals = [v.aval for v in inner.jaxpr.outvars]
        for pos, (var, is_don) in enumerate(
                zip(eqn.invars, donated)):
            if not is_don:
                continue
            aval = var.aval
            n_pass = passthrough.get(id(var), 0)
            if n_pass >= 2:
                findings.append(Finding(
                    rule="HV302", path=path, line=line, scope=scope,
                    message=(
                        f"donated argument {pos} ({_fmt_aval(aval)}) is "
                        f"returned as {n_pass} outputs — the donated "
                        f"buffer would alias multiple result buffers"
                    ),
                ))
            elif n_pass == 0 and not any(
                    _aval_match(aval, oa) for oa in out_avals):
                findings.append(Finding(
                    rule="HV301", path=path, line=line, scope=scope,
                    message=(
                        f"donated argument {pos} "
                        f"({_fmt_aval(aval)}) matches no output "
                        f"shape/dtype — XLA drops the donation silently "
                        f"and the buffer is copied, not reused"
                    ),
                ))
    if entry.donate and not donated_any:
        findings.append(Finding(
            rule="HV303", path=path, line=line, scope=scope,
            message=(
                "entry is registered with donate=True but the traced "
                "graph donates no buffers — the donate_argnums "
                "annotation was lost (wrapper re-jit without donation?)"
            ),
        ))
    return findings


def _aval_match(a, b) -> bool:
    return getattr(a, "shape", None) == getattr(b, "shape", ()) and \
        getattr(a, "dtype", None) == getattr(b, "dtype", None)


def _fmt_aval(a) -> str:
    dt = getattr(a, "dtype", None)
    return f"{getattr(dt, 'name', dt)}{list(getattr(a, 'shape', ()))}"
