"""hgverify orchestration: harvest -> rules -> cost gate -> report.

Same CI surface as hglint's engine: sorted findings, an ``--only`` family
filter that rejects typo'd prefixes, and a ``report_version`` 2 JSON
report with per-rule/severity counts and doc anchors.
"""

from __future__ import annotations

from collections import Counter

from tools.hgverify import costs as costs_mod
from tools.hgverify import rules_jaxpr
from tools.hgverify.harvest import harvest, production_registry
from tools.hgverify.model import (
    Finding,
    doc_anchor,
    parse_only,
    sort_findings,
)

REPORT_VERSION = 2


def run_verify(registry=None, *, costs_path=None, only=None,
               tolerance=None, update_costs=False) -> tuple:
    """Verify every registered entry. Returns ``(findings, meta)`` where
    ``meta`` carries the traces and counts the report/CLI need.

    ``registry=None`` harvests the production registry (importing the
    kernel modules); tests pass a private registry. ``update_costs=True``
    rewrites the budget file from the current measurements instead of
    gating against it."""
    prefixes = parse_only(only)
    if registry is None:
        registry = production_registry()
    traces = harvest(registry)

    cpath = costs_path or costs_mod.DEFAULT_COSTS_PATH
    if tolerance is None:
        # --tolerance beats the costs file's committed tolerance beats
        # the built-in default
        tolerance = costs_mod.load_tolerance(cpath)
    tol = costs_mod.DEFAULT_TOLERANCE if tolerance is None else tolerance

    findings: list = []
    findings += rules_jaxpr.check(traces)
    if update_costs:
        costs_mod.write_costs(traces, cpath)
    else:
        findings += costs_mod.check(traces, costs_mod.load_costs(cpath),
                                    tolerance=tol)
    all_findings = sort_findings(findings)
    if prefixes:
        # HV100 (broken entry) always surfaces: a family filter must not
        # hide that the ground truth itself could not be produced
        findings = [
            f for f in findings
            if f.rule == "HV100"
            or any(f.rule.startswith(p) for p in prefixes)
        ]
    meta = {
        "registered": len(registry),
        "traced": sum(1 for t in traces if t.ok),
        "traces": traces,
        "costs_path": cpath,
        "tolerance": tol,
        "updated_costs": bool(update_costs),
        # pre-filter findings: concordance must cross-tabulate the full
        # ground truth, not whatever --only/--severity left visible
        "all_findings": all_findings,
    }
    return sort_findings(findings), meta


def finding_dict(f: Finding) -> dict:
    return {
        "rule": f.rule, "severity": f.severity, "path": f.path,
        "line": f.line, "scope": f.scope, "message": f.message,
        "doc": doc_anchor(f.rule),
    }


def build_report(findings: list, meta: dict, *, only=None,
                 concordance=None) -> dict:
    """``report_version`` 2 envelope, shape-compatible with hglint's
    (tool/counts/findings keys identical) so CI consumers parse both."""
    by_rule = Counter(f.rule for f in findings)
    by_sev = Counter(f.severity for f in findings)
    report = {
        "tool": "hgverify",
        "report_version": REPORT_VERSION,
        "entries": {
            "registered": meta["registered"],
            "traced": meta["traced"],
        },
        "only": list(parse_only(only)),
        "costs": {
            "path": meta["costs_path"],
            "tolerance": meta["tolerance"],
            "updated": meta["updated_costs"],
        },
        "counts": {
            "total": len(findings),
            "by_rule": dict(sorted(by_rule.items())),
            "by_severity": dict(sorted(by_sev.items())),
        },
        "findings": [finding_dict(f) for f in findings],
    }
    if concordance is not None:
        report["concordance"] = concordance
    return report


def summarize(findings: list, meta: dict) -> str:
    fam = Counter(f.rule[:3] + "xx" for f in findings)
    parts = [
        f"{meta['traced']}/{meta['registered']} entries traced",
        f"{len(findings)} finding(s)" if len(findings) != 1
        else "1 finding",
    ]
    if findings:
        parts.append("by family: " + ", ".join(
            f"{k}={v}" for k, v in sorted(fam.items())
        ))
    return "; ".join(parts)
