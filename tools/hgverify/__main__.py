"""CLI: ``python -m tools.hgverify [--update-costs] [--only HV4] ...``.

Exit status: 0 no findings · 1 findings · 2 usage error (argparse) · 3
analyzer crash — the same crash-vs-finding contract as ``tools.hglint``,
so ``tools/verify.sh`` surfaces analyzer bugs as infrastructure failures.

The trace environment is pinned before JAX's backend initializes: CPU
platform, 8 forced host devices — matching the test harness, so the
committed ``costs.json`` numbers are reproducible everywhere.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import traceback


def _pin_trace_env() -> None:
    """Must run before the first backend touch (works even when a
    sitecustomize already imported jax: backend init is lazy)."""
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
    except Exception:  # pragma: no cover - jax import error surfaces later
        pass


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="hgverify",
        description="jaxpr-level ground-truth contract verification and "
                    "static cost-model regression gate over the "
                    "registered kernel entry points",
    )
    p.add_argument("--costs", metavar="FILE", default=None,
                   help="cost budget file "
                        "(default: tools/hgverify/costs.json)")
    p.add_argument("--update-costs", action="store_true",
                   help="rewrite the budget file from current "
                        "measurements (accepting cost changes), then "
                        "report remaining findings")
    p.add_argument("--tolerance", metavar="FRAC", type=float, default=None,
                   help="relative cost drift tolerance for HV401 "
                        "(default 0.15 = ±15%%)")
    p.add_argument("--only", metavar="PREFIXES", default=None,
                   help="comma-separated rule-id prefixes to report "
                        "(e.g. 'HV4' or 'HV1,HV301'); HV100 always "
                        "surfaces")
    p.add_argument("--concord", action="store_true",
                   help="diff jaxpr ground truth against hglint's AST "
                        "predictions on the entry modules")
    p.add_argument("--concord-paths", metavar="PATHS",
                   default="hypergraphdb_tpu",
                   help="comma-separated hglint paths for --concord")
    p.add_argument("--output", choices=("text", "json"), default="text",
                   help="'json' emits the full machine-readable report")
    p.add_argument("--severity", choices=("error", "warning", "info"),
                   default=None,
                   help="only report findings at this severity")
    args = p.parse_args(argv)

    from tools.hgverify.model import parse_only

    try:
        parse_only(args.only)   # validate prefixes up front
    except ValueError as e:
        p.error(str(e))         # usage error: exit 2

    _pin_trace_env()

    try:
        from tools.hgverify import concord as concord_mod
        from tools.hgverify import engine

        findings, meta = engine.run_verify(
            costs_path=args.costs, only=args.only,
            tolerance=args.tolerance, update_costs=args.update_costs,
        )
        if args.severity:
            findings = [f for f in findings
                        if f.severity == args.severity]
        table = None
        if args.concord:
            # cross-tabulate against the FULL ground truth — --only /
            # --severity filter the report, never the concordance
            table = concord_mod.concord(
                meta["traces"], meta["all_findings"],
                [s for s in args.concord_paths.split(",") if s],
            )
    except Exception:
        traceback.print_exc(file=sys.stderr)
        print("hgverify: internal analyzer crash (exit 3) — this is a "
              "verifier bug, not a finding", file=sys.stderr)
        return 3

    if args.output == "json":
        print(json.dumps(engine.build_report(
            findings, meta, only=args.only, concordance=table,
        ), indent=2))
    else:
        for f in findings:
            print(f.render())
        if args.update_costs:
            print(f"hgverify: wrote cost budgets for {meta['traced']} "
                  f"entries to {meta['costs_path']}")
        print(f"hgverify: {engine.summarize(findings, meta)}")
        if table is not None:
            print(concord_mod.render(table))
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
