"""HV4xx — the static cost-model regression gate.

``tools/hgverify/costs.json`` commits, per registered entry, the XLA
static-cost fingerprint of its exemplar trace: FLOPs, bytes accessed, and
the peak temp-buffer footprint (``memory_analysis``). Any drift beyond
the tolerance (default ±15%) fails the gate — an op whose footprint
silently doubles becomes a lint failure *before* any benchmark runs, and
a legitimate optimization is accepted explicitly via ``--update-costs``
(the same accept-or-fix loop as hglint's baseline).

The numbers are CPU-backend costs under the pinned trace environment
(``JAX_PLATFORMS=cpu``, 8 forced host devices — see ``tools/verify.sh``).
They are not TPU-accurate in absolute terms; they are *deterministic*,
which is the property a regression gate needs.
"""

from __future__ import annotations

import json
import os

from tools.hgverify.harvest import COST_METRICS, rel_path
from tools.hgverify.model import Finding

COSTS_VERSION = 1
DEFAULT_TOLERANCE = 0.15

DEFAULT_COSTS_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "costs.json"
)


def _load(path: str) -> dict:
    if not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != COSTS_VERSION:
        raise ValueError(
            f"costs file {path}: version {data.get('version')} != "
            f"{COSTS_VERSION}"
        )
    return data


def load_costs(path: str) -> dict:
    """name -> {metric: number}; {} when the file does not exist yet."""
    return dict(_load(path).get("entries", {}))


def load_tolerance(path: str):
    """The costs file's committed tolerance (editable alongside the
    budgets; ``--tolerance`` overrides), or None when absent."""
    tol = _load(path).get("tolerance")
    return float(tol) if isinstance(tol, (int, float)) else None


def write_costs(traces: list, path: str) -> dict:
    """Write current measurements for every successfully-traced entry
    (stale names drop out by construction). Returns the entries dict."""
    entries = {
        tr.entry.name: dict(tr.costs)
        for tr in sorted(traces, key=lambda t: t.entry.name)
        if tr.ok and tr.costs is not None
    }
    data = {
        "version": COSTS_VERSION,
        "comment": "hgverify static cost budgets — XLA cost-analysis "
                   "fingerprints of every registered entry's exemplar "
                   "trace (CPU backend, 8 forced host devices). The gate "
                   "fails when a live measurement drifts beyond the "
                   "tolerance. Regenerate with: "
                   "python -m tools.hgverify --update-costs",
        "tolerance": DEFAULT_TOLERANCE,
        "entries": entries,
    }
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(data, fh, indent=2, sort_keys=True)
        fh.write("\n")
    return entries


def check(traces: list, budgets: dict,
          tolerance: float = DEFAULT_TOLERANCE) -> list:
    """HV401 drift / HV402 uncovered / HV403 stale findings."""
    findings = []
    live = set()
    for tr in traces:
        entry = tr.entry
        live.add(entry.name)
        if not tr.ok or tr.costs is None:
            continue  # HV100 already covers broken entries
        path, line, scope = rel_path(entry.path), entry.line, entry.name
        budget = budgets.get(entry.name)
        if budget is None:
            findings.append(Finding(
                rule="HV402", path=path, line=line, scope=scope,
                message=(
                    "entry has no budget in costs.json — cost "
                    "regressions on it are invisible; run "
                    "`python -m tools.hgverify --update-costs` to cover "
                    "it"
                ),
            ))
            continue
        for metric in COST_METRICS:
            cur = tr.costs.get(metric, 0)
            ref = budget.get(metric, 0)
            if not _within(cur, ref, tolerance):
                direction = "grew" if cur > ref else "shrank"
                findings.append(Finding(
                    rule="HV401", path=path, line=line, scope=scope,
                    message=(
                        f"{metric} {direction} {ref} -> {cur} "
                        f"({_pct(cur, ref)} beyond the "
                        f"±{tolerance:.0%} tolerance) — fix the "
                        f"regression, or accept the new cost with "
                        f"--update-costs"
                    ),
                ))
    for name in sorted(set(budgets) - live):
        findings.append(Finding(
            rule="HV403", path="tools/hgverify/costs.json", line=1,
            scope=name,
            message=(
                f"costs.json budgets entry {name!r} but no such entry "
                f"point is registered — stale budgets hide coverage "
                f"loss; regenerate with --update-costs"
            ),
        ))
    return findings


def _within(cur, ref, tol: float) -> bool:
    if ref == 0:
        return cur == 0
    return abs(cur - ref) <= tol * abs(ref)


def _pct(cur, ref) -> str:
    if ref == 0:
        return "∞"
    return f"{abs(cur - ref) / abs(ref):+.0%}".lstrip("+")
