#!/usr/bin/env bash
# hglint repo gate: exits nonzero on any NEW hazard beyond the checked-in
# baseline (tools/hglint/baseline.json). Tier-1 enforces the same check via
# tests/test_hglint.py::test_repo_gate_passes_with_baseline.
#
# Exit codes: 0 clean · 1 new findings · >= 2 analyzer crash / usage error
# (a crash is an infrastructure failure, NOT a finding — CI must fail it
# loudly instead of reporting "1 finding").
#
# Every diagnostic carries its rule-family docs anchor
# (e.g. "[README.md#hg5xx-vmem-budgets]") — see the README rule table.
#
# Usage: tools/lint.sh [extra hglint args]
#   tools/lint.sh --severity error     # only hard errors
#   tools/lint.sh --only HG5           # one rule family, fast local run
#   tools/lint.sh --output json        # machine-readable CI report
set -uo pipefail
cd "$(dirname "$0")/.."
python -m tools.hglint hypergraphdb_tpu \
    --baseline tools/hglint/baseline.json "$@"
rc=$?
if [ "$rc" -ge 2 ]; then
    echo "tools/lint.sh: hglint analyzer crashed (exit $rc);" \
         "fix the analyzer before trusting this gate" >&2
fi
exit "$rc"
