#!/usr/bin/env bash
# hglint repo gate: exits nonzero on any NEW hazard beyond the checked-in
# baseline (tools/hglint/baseline.json). Tier-1 enforces the same check via
# tests/test_hglint.py::test_repo_gate_passes_with_baseline.
#
# Usage: tools/lint.sh [extra hglint args]
#   tools/lint.sh --severity error     # only hard errors
#   tools/lint.sh --json               # machine-readable output
set -euo pipefail
cd "$(dirname "$0")/.."
exec python -m tools.hglint hypergraphdb_tpu \
    --baseline tools/hglint/baseline.json "$@"
