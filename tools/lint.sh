#!/usr/bin/env bash
# hglint repo gate: exits nonzero on any NEW hazard beyond the checked-in
# baseline (tools/hglint/baseline.json). Tier-1 enforces the same check via
# tests/test_hglint.py::test_repo_gate_passes_with_baseline.
#
# Exit codes: 0 clean · 1 new findings · >= 2 analyzer crash / usage error
# (a crash is an infrastructure failure, NOT a finding — CI must fail it
# loudly instead of reporting "1 finding").
#
# Every diagnostic carries its rule-family docs anchor
# (e.g. "[README.md#hg5xx-vmem-budgets]") — see the README rule table.
#
# After a clean-enough run (exit < 2) the full machine-readable report is
# written as a CI artifact to $HGLINT_REPORT (default
# /tmp/hglint_report.json); skipped when the caller already picked an
# output mode or is writing a baseline.
#
# Usage: tools/lint.sh [extra hglint args]
#   tools/lint.sh --severity error     # only hard errors
#   tools/lint.sh --only HG5           # one rule family, fast local run
#   tools/lint.sh --only HG10          # exception-flow family only
#                                      # (family-aware: never HG101-107)
#   tools/lint.sh --only HG11          # wire-contract family only
#                                      # (HG1101-1105, zero baseline)
#   tools/lint.sh --output json        # machine-readable CI report
#   tools/lint.sh --pre-commit         # fast lane: findings only in files
#                                      # changed vs HEAD (analysis stays
#                                      # whole-program)
set -uo pipefail
cd "$(dirname "$0")/.."

report="${HGLINT_REPORT:-/tmp/hglint_report.json}"
emit_artifact=1
args=()
for a in "$@"; do
    case "$a" in
        --pre-commit) args+=(--diff-base HEAD) ;;
        --output|--output=*|--json|--write-baseline|--write-baseline=*)
            emit_artifact=0; args+=("$a") ;;
        *) args+=("$a") ;;
    esac
done

python -m tools.hglint hypergraphdb_tpu \
    --baseline tools/hglint/baseline.json ${args[@]+"${args[@]}"}
rc=$?
if [ "$rc" -ge 2 ]; then
    echo "tools/lint.sh: hglint analyzer crashed (exit $rc);" \
         "fix the analyzer before trusting this gate" >&2
    exit "$rc"
fi

if [ "$emit_artifact" -eq 1 ]; then
    python -m tools.hglint hypergraphdb_tpu \
        --baseline tools/hglint/baseline.json --output json \
        ${args[@]+"${args[@]}"} > "$report"
    arc=$?
    if [ "$arc" -ge 2 ]; then
        echo "tools/lint.sh: hglint crashed while writing the CI report" \
             "(exit $arc); fix the analyzer before trusting this gate" >&2
        exit "$arc"
    fi
fi
exit "$rc"
