#!/usr/bin/env bash
# hgfault chaos gate: the short deterministic multi-seed fault-injection
# suite — registry/breaker units, serve failure paths, peer self-healing,
# crash-atomic checkpoints, the kill→reopen→replay recovery drill, and
# the 5-seed chaos soak (serve + concurrent ingest + replication under a
# pre-drawn fault schedule; same seed → same fault sequence).
#
# The long combined soak is marked `slow` (excluded here, mirroring the
# PR-4 tier-1 convention); run it with: tools/chaos.sh -m slow
#
# Usage: tools/chaos.sh [extra pytest args]
#   tools/chaos.sh -k breaker          # one area, fast local run
#   tools/chaos.sh -m slow             # the long soak only
set -uo pipefail
cd "$(dirname "$0")/.."
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_fault.py \
    tests/test_serve_fault.py \
    tests/test_peer_fault.py \
    tests/test_recovery_drill.py \
    tests/test_chaos.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/chaos.sh: chaos gate failed (exit $rc)" >&2
fi
exit "$rc"
