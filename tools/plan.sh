#!/usr/bin/env bash
# hgplan gate: the cost-based-planner suite — the cardinality-estimator
# oracle suite (exact-flagged estimates EQUAL brute-force counts;
# model estimates stay inside bounded relative error on uniform AND
# hub-heavy families), the planner differential suite (every enumerable
# candidate shape forced through submit_planned returns exactly
# graph.find_all's match set), the feedback-loop suite (the drift
# digest demonstrably shrinks median est-vs-actual error on a replayed
# trace, is LRU/clamp-bounded, and the sentinel guard vetoes a
# correction that steers onto a degraded lane), then a LIVE smoke on a
# seeded skewed graph: the planner must pick the sparse anchor, the
# EXPLAIN record must carry plan.est_rows / plan.actual_rows, and the
# planned path must run >= 2x faster than the worst candidate lane
# (forced via force_shape, timed on the same runtime).
#
# Sits beside lint.sh (AST hazards), verify.sh (jaxpr ground truth),
# join.sh (the join engine the planner prices), perf.sh (the sentinel
# whose violating set the guard veto reads), and obs.sh: this one
# gates the planning subsystem.
#
# Usage: tools/plan.sh [extra pytest args]
#   tools/plan.sh -k feedback          # one area, fast local run
set -uo pipefail
cd "$(dirname "$0")/.."

JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python -m pytest \
    tests/test_plan_stats.py \
    tests/test_planner.py \
    tests/test_plan_feedback.py \
    -q -m 'not slow' -p no:cacheprovider "$@"
rc=$?
if [ "$rc" -ne 0 ]; then
    echo "tools/plan.sh: plan suites failed (exit $rc)" >&2
    exit "$rc"
fi

# -- live smoke: skewed graph, cheap anchor chosen, planned path beats
#    the worst candidate lane by >= 2x, EXPLAIN carries est/actual ------------
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" python - <<'PY'
import json
import time

import numpy as np

from hypergraphdb_tpu import HyperGraph, obs
from hypergraphdb_tpu.obs.perf import default_baseline_path, load_baseline
from hypergraphdb_tpu.plan import QueryPlanner
from hypergraphdb_tpu.query import conditions as c
from hypergraphdb_tpu.serve import ServeConfig, ServeRuntime

obs.enable()  # EXPLAIN records need the tracer
g = HyperGraph()
r = np.random.default_rng(11)
n = 4000
nodes = [int(h) for h in g.bulk_import(values=np.arange(n).tolist())]
hub, rare = nodes[0], nodes[-1]
g.bulk_import(
    values=[int(100_000 + i) for i in range(3 * n)],
    target_lists=[[hub, nodes[1 + int(r.integers(n - 2))]]
                  for _ in range(3 * n)],
)
g.add_link([rare, nodes[1]], value=500)
g.add_link([rare, nodes[2]], value=501)

rt = ServeRuntime(g, ServeConfig(buckets=(64,), manual=True,
                                 max_linger_s=0.0, top_r=256))
# DEFAULT priors for the timing assertion — the committed baselines are
# coarse CPU-smoke anchors; pricing a wall-clock gate from them would
# couple this smoke to whatever hardware last recorded a bench
rt.attach_planner(QueryPlanner(g))

# ... but the baseline-coupling contract is still checked live: a
# planner built from the committed record must price the join lane at
# the SAME p50 bench.py --seed-baseline wrote there (the c11 open-loop
# record after PR 20)
pb = load_baseline(default_baseline_path())
pl = QueryPlanner.from_committed_baseline(g)
assert pl._priors["join"] == pb["lanes"]["join"]["p50_s"], (
    pl._priors["join"], pb["lanes"]["join"])
baseline_join = {"p50_s": pb["lanes"]["join"]["p50_s"],
                 "note": pb["lanes"]["join"].get("note")}


def drain():
    while rt.step(drain=True):
        pass


def timed(cond, shape=None, reps=3):
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        fut = rt.submit_planned(cond, force_shape=shape, explain=True)
        drain()
        fut.result(timeout=0)
        best = min(best, time.perf_counter() - t0)
    return best, fut


# -- choice: a conjunction anchored at BOTH ends of the skew must plan
#    through the sparse anchor, not the hub --------------------------------
cond_anchor = c.And(c.Incident(rare), c.Incident(hub))
truth_anchor = sorted(int(h) for h in g.find_all(cond_anchor))
choice = rt.planner.plan(cond_anchor)
est = rt.planner.estimator
assert choice.est_rows <= est.degree(rare), (
    f"planner did not anchor at the sparse end: est_rows="
    f"{choice.est_rows} > degree(rare)={est.degree(rare)}")
assert choice.est_rows < est.degree(hub)
fut = rt.submit_planned(cond_anchor)
drain()
assert list(fut.result(timeout=0).matches) == truth_anchor

# -- cost: a narrow value window AND the hub's co-incidence. The exact
#    window estimate (a handful of rows) routes the planner to the
#    range lane; the join candidate must expand the hub's 3n-wide
#    co-row — the expensive plan the cost model exists to avoid -----------
cond = c.And(c.CoIncident(hub), c.AtomValue(10, "gte"),
             c.AtomValue(20, "lte"))
truth = sorted(int(h) for h in g.find_all(cond))
assert truth, "smoke graph produced an empty window"

shapes = rt.planner.shapes_for(cond)
assert "join" in shapes, shapes
for shape in shapes:          # compile/warm every lane off the clock
    fut = rt.submit_planned(cond, force_shape=shape)
    drain()
    assert list(fut.result(timeout=0).matches) == truth, shape

lane_s = {shape: timed(cond, shape)[0] for shape in shapes}
planned_s, fut = timed(cond)
res = fut.result(timeout=0)
assert list(res.matches) == truth
for key in ("est_rows", "actual_rows", "shape", "cost"):
    assert key in res.plan, (key, res.plan)
ex = fut.explain
assert ex["plan"]["shape"] == res.plan["shape"]
worst_shape = max(lane_s, key=lane_s.get)
speedup = lane_s[worst_shape] / planned_s
assert speedup >= 2.0, (
    f"planned path only {speedup:.2f}x faster than worst candidate "
    f"{worst_shape} ({lane_s[worst_shape]*1e3:.2f}ms vs "
    f"{planned_s*1e3:.2f}ms)")
rt.close()
g.close()
print("tools/plan.sh smoke:", json.dumps({
    "chosen": res.plan["shape"],
    "est_rows": res.plan["est_rows"],
    "actual_rows": res.plan["actual_rows"],
    "planned_ms": round(planned_s * 1e3, 2),
    "worst_candidate": worst_shape,
    "worst_ms": round(lane_s[worst_shape] * 1e3, 2),
    "speedup_vs_worst": round(speedup, 1),
    "candidates_ms": {k: round(v * 1e3, 2)
                      for k, v in sorted(lane_s.items())},
    "baseline_join_prior": baseline_join,
}))
PY
smoke_rc=$?
if [ "$smoke_rc" -ne 0 ]; then
    echo "tools/plan.sh: live planner smoke failed (exit $smoke_rc)" >&2
    exit "$smoke_rc"
fi
echo "tools/plan.sh: plan gate green"
exit 0
